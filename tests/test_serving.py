"""The serving subsystem: engine parity, bucket discipline, batcher,
service loop.

The load-bearing guarantees: (1) checkpoint -> ``ServingEngine.load``
-> predictions BITWISE equal to what ``fedcore/evaluate.py`` computes
in-memory on the same inputs (both checkpoint layouts, both the
pre-mapped and fused-RFF paths); (2) a warmed engine serves any
mixed-size stream with zero new compiles; (3) the stdlib service loop
routes every request to its own result, sheds on deadline and on queue
overflow, and never splits a request across batches.
"""

import sys
import time

import numpy as np
import pytest

from fedamw_tpu.algorithms import FedAvg, prepare_setup
from fedamw_tpu.data import load_dataset
from fedamw_tpu.fedcore import make_evaluator
from fedamw_tpu.serving import (DeadlineExceeded, MicroBatcher, Overloaded,
                                ServiceStopped, ServingEngine,
                                ServingService, bucket_for, coalesce,
                                infer_model, split_results)
from fedamw_tpu.utils.checkpoint import save_checkpoint


def _trained(kernel_type="linear", D=64, parts=4, seed=3):
    ds = load_dataset("digits", num_partitions=parts, alpha=0.5)
    setup = prepare_setup(ds, D=D, kernel_type=kernel_type,
                          kernel_par=0.1, seed=seed,
                          rng=np.random.RandomState(seed))
    res = FedAvg(setup, lr=0.5, epoch=1, round=2, seed=0,
                 lr_mode="constant", return_state=True)
    return ds, setup, res


# -- bucket ladder ----------------------------------------------------

def test_bucket_for_picks_smallest_rung():
    assert bucket_for(1, (1, 8, 64)) == 1
    assert bucket_for(2, (1, 8, 64)) == 8
    assert bucket_for(8, (1, 8, 64)) == 8
    assert bucket_for(9, (1, 8, 64)) == 64
    with pytest.raises(ValueError, match="exceeds"):
        bucket_for(65, (1, 8, 64))
    with pytest.raises(ValueError, match="at least one"):
        bucket_for(0, (1, 8, 64))


def test_infer_model_from_params():
    assert infer_model({"w": np.zeros((3, 5))}).name == "linear"
    m = infer_model({"w1": np.zeros((16, 5)), "b1": np.zeros(16),
                     "w2": np.zeros((3, 16))})
    assert m.name == "mlp16"
    with pytest.raises(ValueError, match="explicitly"):
        infer_model({"conv1": np.zeros((3, 3, 1, 8))})


def test_conv_model_serves_with_explicit_input_dim():
    """Conv pytrees hide the raw width (the 'w' head sees post-conv
    features), so the engine needs model= AND input_dim= — with both,
    it serves raw image rows bitwise-equal to the in-memory apply."""
    import jax

    from fedamw_tpu.models.conv import conv_model

    model = conv_model((4,))
    d, C = 64, 3  # 8x8 images
    params = model.init(jax.random.PRNGKey(0), d, C)
    engine = ServingEngine(params, model=model, input_dim=d,
                           buckets=(8,))
    assert engine.input_dim == d
    X = np.random.RandomState(9).randn(6, d).astype(np.float32)
    np.testing.assert_array_equal(engine.predict(X),
                                  np.asarray(model.apply(params, X)))


# -- checkpoint -> engine parity (satellite: both layouts) ------------

@pytest.mark.parametrize("layout", ["orbax", "pickle"])
def test_checkpoint_roundtrip_serving_parity(tmp_path, monkeypatch,
                                             layout):
    """save_checkpoint -> ServingEngine.load -> predictions bitwise
    equal to the in-memory model on the same inputs, and accuracy
    identical to make_evaluator's, for BOTH checkpoint layouts."""
    if layout == "pickle":
        # poison the orbax import so save/load take the pickle branch
        monkeypatch.setitem(sys.modules, "orbax", None)
        monkeypatch.setitem(sys.modules, "orbax.checkpoint", None)
    ds, setup, res = _trained(kernel_type="linear")
    where = save_checkpoint(str(tmp_path / "ck"), res["params"],
                            p=res["p"])
    assert ("state.pkl" in where) == (layout == "pickle")

    engine = ServingEngine.load(str(tmp_path / "ck"), buckets=(1, 8, 512))
    X = np.asarray(setup.X_test)
    got = engine.predict(X)
    want = np.asarray(setup.model.apply(res["params"], setup.X_test))
    np.testing.assert_array_equal(got, want)

    evaluate = make_evaluator(setup.model.apply, setup.task)
    _, acc = evaluate(res["params"], setup.X_test, setup.y_test)
    served_acc = 100.0 * np.mean(
        np.argmax(got, -1) == np.asarray(setup.y_test))
    assert abs(served_acc - float(acc)) < 1e-4


def test_fused_rff_serving_matches_evaluate(tmp_path):
    """The raw-input path: a checkpoint saved with the RFF draw serves
    RAW features through the fused cos(XW+b) predictor, bitwise equal
    to mapping then applying in-memory (rff_map is inlined under the
    engine's jit, same expression)."""
    ds, setup, res = _trained(kernel_type="gaussian", D=128)
    save_checkpoint(str(tmp_path / "ck"), res["params"], p=res["p"],
                    rff=setup.rff)
    engine = ServingEngine.load(str(tmp_path / "ck"), buckets=(512,))
    assert engine.rff is not None
    assert engine.input_dim == ds.d  # raw width, not the RFF width
    got = engine.predict(np.asarray(ds.X_test, np.float32))
    want = np.asarray(setup.model.apply(res["params"], setup.X_test))
    np.testing.assert_array_equal(got, want)


def test_fedamw_checkpoint_serving_accuracy_parity(tmp_path):
    """The acceptance-criteria parity: a FedAMW-trained checkpoint
    (learned mixture weights, RFF draw included — what exp.py
    --save_models writes) served through the engine reproduces
    fedcore/evaluate.py's test accuracy EXACTLY."""
    from fedamw_tpu.algorithms import FedAMW

    ds = load_dataset("digits", num_partitions=4, alpha=0.5)
    setup = prepare_setup(ds, D=128, kernel_par=0.1, seed=5,
                          rng=np.random.RandomState(5))
    res = FedAMW(setup, lr=0.5, epoch=1, round=2, lambda_reg=1e-4,
                 lr_p=1e-2, seed=0, lr_mode="constant",
                 return_state=True)
    save_checkpoint(str(tmp_path / "amw"), res["params"], p=res["p"],
                    round_idx=2, rff=setup.rff)

    engine = ServingEngine.load(str(tmp_path / "amw"))
    evaluate = make_evaluator(setup.model.apply, setup.task)
    _, acc = evaluate(res["params"], setup.X_test, setup.y_test)
    logits = engine.predict(np.asarray(ds.X_test, np.float32))
    served_acc = 100.0 * np.mean(
        np.argmax(logits, -1) == np.asarray(setup.y_test))
    assert served_acc == pytest.approx(float(acc), abs=1e-4)
    # and the learned (non-uniform) mixture weights round-tripped too
    from fedamw_tpu.utils.checkpoint import load_checkpoint

    state = load_checkpoint(str(tmp_path / "amw"))
    np.testing.assert_array_equal(np.asarray(state["p"]),
                                  np.asarray(res["p"]))


def test_feature_dtype_matches_narrow_feature_training():
    """A bf16-feature training run (prepare_setup(feature_dtype=...)
    maps via rff_map_to) is served with parity by passing the same
    dtype to the engine: fused cast matches the training-side mapped
    features bitwise (code-review finding — without the dtype the
    engine would silently score f32 features against a bf16-trained
    head)."""
    import jax
    import jax.numpy as jnp

    from fedamw_tpu.ops.rff import rff_map_to, rff_params

    rng = np.random.RandomState(8)
    W, b = rff_params(jax.random.PRNGKey(0), 16, 32, 1.0)
    params = {"w": rng.randn(3, 32).astype(np.float32)}
    X = rng.randn(20, 16).astype(np.float32)
    eng = ServingEngine(params, rff=(W, b), buckets=(64,),
                        feature_dtype=jnp.bfloat16)
    feats = rff_map_to(jnp.asarray(X), W, b, jnp.bfloat16)
    want = np.asarray(jnp.asarray(feats) @ jnp.asarray(params["w"]).T)
    np.testing.assert_array_equal(eng.predict(X), want)
    # and the dtype genuinely changes the result vs the f32 path
    f32 = ServingEngine(params, rff=(W, b), buckets=(64,))
    assert not np.array_equal(eng.predict(X), f32.predict(X))
    # pre-mapped path: the dtype must apply there too, not silently
    # no-op (a bf16-feature linear-kernel run has no RFF draw at all)
    pre = ServingEngine(params, buckets=(64,),
                        feature_dtype=jnp.bfloat16)
    feats_np = np.asarray(feats, np.float32)  # bf16->f32 is lossless
    np.testing.assert_array_equal(pre.predict(feats_np), want)


def test_feature_dtype_marker_round_trips_through_checkpoint(tmp_path):
    """save_checkpoint(feature_dtype=...) persists the narrow-feature
    marker and ServingEngine.load applies it automatically — no
    operator memory required for bf16-parity serving."""
    import jax
    import jax.numpy as jnp

    from fedamw_tpu.ops.rff import rff_map_to, rff_params

    rng = np.random.RandomState(10)
    W, b = rff_params(jax.random.PRNGKey(2), 16, 32, 1.0)
    params = {"w": rng.randn(3, 32).astype(np.float32)}
    save_checkpoint(str(tmp_path / "ck"), params, rff=(W, b),
                    feature_dtype=jnp.bfloat16)
    eng = ServingEngine.load(str(tmp_path / "ck"), buckets=(64,))
    assert str(eng.feature_dtype) == "bfloat16"
    X = rng.randn(12, 16).astype(np.float32)
    feats = rff_map_to(jnp.asarray(X), W, b, jnp.bfloat16)
    want = np.asarray(jnp.asarray(feats) @ jnp.asarray(params["w"]).T)
    np.testing.assert_array_equal(eng.predict(X), want)


def test_padding_rows_are_inert():
    """A bucket-padded batch returns the same logits for the valid rows
    as an exact-fit call — rows are independent through the network."""
    rng = np.random.RandomState(0)
    params = {"w": rng.randn(3, 16).astype(np.float32)}
    engine = ServingEngine(params, buckets=(8, 64))
    X = rng.randn(5, 16).astype(np.float32)  # pads 5 -> 8
    np.testing.assert_array_equal(
        engine.predict(X), engine.predict(np.concatenate([X, X]))[:5])


def test_single_row_and_oversized_requests():
    rng = np.random.RandomState(1)
    params = {"w": rng.randn(3, 16).astype(np.float32)}
    engine = ServingEngine(params, buckets=(1, 8))
    row = rng.randn(16).astype(np.float32)
    out = engine.predict(row)
    assert out.shape == (3,)  # single row in, single row out
    np.testing.assert_array_equal(out, engine.predict(row[None, :])[0])
    # 20 rows > max bucket 8: chunked transparently
    X = rng.randn(20, 16).astype(np.float32)
    assert engine.predict(X).shape == (20, 3)
    np.testing.assert_array_equal(engine.predict(X)[3:7],
                                  engine.predict(X[3:7]))
    with pytest.raises(ValueError, match="expected"):
        engine.predict(rng.randn(4, 7))


def test_warmed_engine_serves_mixed_stream_with_zero_recompiles():
    rng = np.random.RandomState(2)
    params = {"w": rng.randn(4, 32).astype(np.float32)}
    engine = ServingEngine(params, buckets=(1, 8, 64))
    warm = engine.warmup()
    assert warm == engine.compile_count == 3  # one program per rung
    for n in (1, 2, 3, 7, 8, 9, 33, 64, 64, 5, 150, 1):
        engine.predict(rng.randn(n, 32).astype(np.float32))
    assert engine.compile_count == warm


def test_engine_on_serving_mesh_matches_single_device():
    """The GSPMD serving path: params replicated, batch axis sharded
    P('batch', None) over the 8-device virtual mesh — same logits as
    the unsharded engine, buckets rounded up to device multiples."""
    from fedamw_tpu.parallel import make_serving_mesh

    rng = np.random.RandomState(3)
    params = {"w": rng.randn(3, 16).astype(np.float32)}
    mesh = make_serving_mesh()
    sharded = ServingEngine(params, buckets=(1, 8, 64), mesh=mesh)
    assert sharded.buckets == (8, 64)  # rung 1 rounds up to 8 shards
    plain = ServingEngine(params, buckets=(8, 64))
    X = rng.randn(40, 16).astype(np.float32)
    np.testing.assert_array_equal(sharded.predict(X), plain.predict(X))


# -- batcher ----------------------------------------------------------

def test_coalesce_split_roundtrip():
    rng = np.random.RandomState(4)
    payloads = [rng.randn(16).astype(np.float32),
                rng.randn(3, 16).astype(np.float32),
                rng.randn(1, 16).astype(np.float32)]
    X, spans = coalesce(payloads)
    assert X.shape == (5, 16)
    outs = split_results(X, spans)  # identity engine
    np.testing.assert_array_equal(outs[0], payloads[0])  # 1-D restored
    np.testing.assert_array_equal(outs[1], payloads[1])
    assert outs[2].shape == (1, 16)


def test_micro_batcher_routes_results():
    rng = np.random.RandomState(5)
    params = {"w": rng.randn(3, 16).astype(np.float32)}
    engine = ServingEngine(params, buckets=(8, 64))
    payloads = [rng.randn(k, 16).astype(np.float32) for k in (2, 5, 1)]
    outs = MicroBatcher(engine).run(payloads)
    for x, o in zip(payloads, outs):
        np.testing.assert_array_equal(o, engine.predict(x))
    assert MicroBatcher(engine).run([]) == []


def test_drain_never_splits_a_request_and_hands_back_holdover():
    import queue as queue_mod

    from fedamw_tpu.serving import drain

    q = queue_mod.Queue()
    for k in (4, 3):
        q.put(np.zeros((k, 8), np.float32))
    batch, held = drain(q, np.zeros((2, 8), np.float32), max_rows=8,
                        max_wait=0.0)
    # 2 + 4 fit; the 3-row request would exceed 8 -> handed back as the
    # next batch's seed (NOT re-queued at the tail, where a sustained
    # stream of fresh arrivals could starve it past its deadline)
    assert [b.shape[0] for b in batch] == [2, 4]
    assert held is not None and held.shape[0] == 3
    assert q.qsize() == 0
    # exact-fit and timeout drains have no holdover
    batch, held = drain(q, np.zeros((8, 8), np.float32), max_rows=8,
                        max_wait=0.0)
    assert [b.shape[0] for b in batch] == [8] and held is None


# -- service loop -----------------------------------------------------

def _engine(seed=6, d=16, C=3, buckets=(8, 64)):
    rng = np.random.RandomState(seed)
    return ServingEngine({"w": rng.randn(C, d).astype(np.float32)},
                         buckets=buckets)


def test_service_resolves_each_future_with_its_own_logits():
    engine = _engine()
    rng = np.random.RandomState(7)
    payloads = [rng.randn(k, 16).astype(np.float32)
                for k in (1, 4, 2, 8, 3)]
    with ServingService(engine, max_wait_ms=1.0) as svc:
        futs = [svc.submit(x) for x in payloads]
        for x, f in zip(payloads, futs):
            np.testing.assert_array_equal(f.result(timeout=30),
                                          engine.predict(x))
        assert svc.metrics.requests_served == len(payloads)
        assert svc.metrics.latency.count == len(payloads)


def test_service_sheds_expired_deadline():
    engine = _engine()
    svc = ServingService(engine, max_wait_ms=1.0)
    # submit BEFORE start: the request sits queued past its deadline,
    # deterministically (no race against a live worker)
    svc._thread = object()  # satisfy the started check for submit
    fut = svc.submit(np.zeros((2, 16), np.float32), timeout_s=0.0)
    time.sleep(0.01)
    svc._thread = None
    with svc:
        with pytest.raises(DeadlineExceeded):
            fut.result(timeout=30)
    assert svc.metrics.shed_deadline == 1


def test_service_sheds_on_queue_overflow():
    engine = _engine()
    svc = ServingService(engine, max_queue=2)
    svc._thread = object()  # queue fills while no worker drains
    svc.submit(np.zeros((1, 16), np.float32))
    svc.submit(np.zeros((1, 16), np.float32))
    with pytest.raises(Overloaded):
        svc.submit(np.zeros((1, 16), np.float32))
    assert svc.metrics.shed_overload == 1
    assert svc.metrics.queue_depth_peak >= 2
    svc._thread = None
    with svc:  # the two accepted requests still drain gracefully
        pass
    assert svc.metrics.requests_served == 2


def test_service_stop_without_drain_sheds_backlog():
    engine = _engine()
    svc = ServingService(engine)
    svc._thread = object()
    fut = svc.submit(np.zeros((1, 16), np.float32))
    svc._thread = None
    svc.start()
    svc.stop(drain_queue=False)
    # the backlog future is resolved either way (served if the worker
    # got to it first, shed otherwise) — never left hanging
    assert fut.done()


def test_service_propagates_engine_errors_and_worker_survives():
    """An engine-side failure resolves every future in the batch with
    the error and leaves the worker alive for later traffic — never a
    silently dead thread with stranded futures."""
    engine = _engine()
    real_predict = engine.predict
    state = {"failed": False}

    def flaky(X):
        if not state["failed"]:
            state["failed"] = True
            raise RuntimeError("transient engine failure")
        return real_predict(X)

    engine.predict = flaky
    svc = ServingService(engine, max_wait_ms=20.0)
    # queue both before the worker starts so they land in ONE batch
    svc._thread = object()
    f1 = svc.submit(np.zeros((2, 16), np.float32))
    f2 = svc.submit(np.zeros((3, 16), np.float32))
    svc._thread = None
    with svc:
        for f in (f1, f2):
            with pytest.raises(RuntimeError, match="transient"):
                f.result(timeout=30)
        ok = svc.submit(np.zeros((2, 16), np.float32))
        np.testing.assert_array_equal(
            ok.result(timeout=30),
            real_predict(np.zeros((2, 16), np.float32)))


def test_submit_requires_started_service():
    with pytest.raises(RuntimeError, match="not started"):
        ServingService(_engine()).submit(np.zeros((1, 16), np.float32))


def test_cancelled_future_does_not_kill_the_worker():
    """A caller cancelling its pending Future must not crash the
    worker on resolution (set_result on a cancelled Future raises
    InvalidStateError) — the rest of the batch and all later traffic
    keep being served (code-review finding, reproduced live)."""
    engine = _engine()
    svc = ServingService(engine, max_wait_ms=20.0)
    svc._thread = object()  # queue before start: same batch, no races
    f1 = svc.submit(np.zeros((2, 16), np.float32))
    f2 = svc.submit(np.ones((2, 16), np.float32))
    assert f1.cancel()
    svc._thread = None
    with svc:
        np.testing.assert_array_equal(
            f2.result(timeout=30),
            engine.predict(np.ones((2, 16), np.float32)))
        later = svc.submit(np.ones((3, 16), np.float32))
        assert later.result(timeout=30).shape == (3, 3)


def test_submit_refused_once_stopping():
    """Refusing new work after stop() begins is what guarantees the
    worker's final drain terminates under sustained submit load."""
    with ServingService(_engine()) as svc:
        svc._stop.set()
        with pytest.raises(ServiceStopped, match="stopping"):
            svc.submit(np.zeros((1, 16), np.float32))
        svc._stop.clear()


def test_stop_sweep_resolves_requests_the_worker_never_saw():
    """A submit racing stop() can land its request after the worker
    exits; the post-join sweep must resolve that Future (served on a
    graceful stop, shed on drain_queue=False) instead of stranding the
    caller forever and leaking a depth slot (code-review finding)."""
    from concurrent.futures import Future

    from fedamw_tpu.serving.service import _Request

    for drain_queue, check in ((True, "served"), (False, "shed")):
        engine = _engine()
        svc = ServingService(engine)
        fut: Future = Future()
        x = np.ones((2, 16), np.float32)
        # simulate the race: the request lands post-join, as if submit
        # passed the liveness check concurrently with stop()
        svc._q.put(_Request(x=x, future=fut, t_submit=0.0, deadline=None))
        with svc._depth_lock:
            svc._depth += 1
        svc._sweep_leftovers(drain_queue)
        if check == "served":
            np.testing.assert_array_equal(fut.result(timeout=5),
                                          engine.predict(x))
            # sweep-served requests count in metrics like worker-served
            assert svc.metrics.requests_served == 1
            assert svc.metrics.latency.count == 1
        else:
            # shutdown shed is NOT a deadline violation: distinct
            # exception and counter, so operators and retry logic can
            # tell a deliberate stop from a timeout
            with pytest.raises(ServiceStopped):
                fut.result(timeout=5)
            assert svc.metrics.shed_shutdown == 1
            assert svc.metrics.shed_deadline == 0
        assert svc._depth == 0  # the capacity slot was reclaimed

    # an already-expired leftover is shed, not served late — the sweep
    # honors deadlines exactly like the worker's dequeue check
    engine = _engine()
    svc = ServingService(engine)
    fut = Future()
    svc._q.put(_Request(x=np.ones((2, 16), np.float32), future=fut,
                        t_submit=0.0, deadline=0.0))
    with svc._depth_lock:
        svc._depth += 1
    svc._sweep_leftovers(True)
    with pytest.raises(DeadlineExceeded, match="expired"):
        fut.result(timeout=5)
    assert svc.metrics.shed_deadline == 1 and svc._depth == 0


def test_submit_rejects_malformed_payload_synchronously():
    """A 0-d/3-d or wrong-width payload must fail in the CALLER's
    thread: queued, it would poison the coalesced batch and fail OTHER
    callers' valid requests alongside (code-review finding)."""
    with ServingService(_engine()) as svc:
        for bad in (1.0, np.zeros((2, 3, 4), np.float32),
                    np.zeros((2, 7), np.float32),   # width != 16
                    np.zeros((0, 16), np.float32),  # zero rows
                    np.zeros(7, np.float32)):
            with pytest.raises(ValueError, match="request must be"):
                svc.submit(bad)
        assert svc.metrics.shed_overload == 0  # rejected, not shed


def test_overload_bound_is_atomic_under_concurrent_submits():
    """The max_queue bound must hold under a concurrent submit storm
    (the depth check is a locked counter, not a qsize()-then-put
    race): accepted requests never exceed max_queue before the worker
    starts draining."""
    import threading as th

    engine = _engine()
    svc = ServingService(engine, max_queue=8)
    svc._thread = object()  # no worker: the bound alone limits depth
    accepted, errs = [], []

    def storm():
        try:
            accepted.append(svc.submit(np.zeros((1, 16), np.float32)))
        except Overloaded:
            errs.append(1)

    threads = [th.Thread(target=storm) for _ in range(32)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(accepted) == 8 and len(errs) == 24
    assert svc.metrics.shed_overload == 24
    svc._thread = None
    with svc:  # accepted backlog drains gracefully
        for f in accepted:
            f.result(timeout=30)
    assert svc.metrics.requests_served == 8


# -- transient-dispatch retry (ISSUE 2 satellite) ---------------------

def test_transient_engine_failure_retried_with_backoff():
    """A flapping engine backend (here: two UNAVAILABLE failures, then
    success) is absorbed by the bounded retry — every future resolves
    with its result, and the retry counter lands in the metrics
    snapshot."""
    engine = _engine()
    real_predict = engine.predict
    state = {"fails": 2}

    def flaky(X):
        if state["fails"] > 0:
            state["fails"] -= 1
            raise RuntimeError("UNAVAILABLE: backend tunnel hiccup")
        return real_predict(X)

    engine.predict = flaky
    with ServingService(engine, max_wait_ms=20.0, retries=2,
                        retry_backoff_ms=1.0) as svc:
        f1 = svc.submit(np.zeros((2, 16), np.float32))
        f2 = svc.submit(np.ones((3, 16), np.float32))
        np.testing.assert_array_equal(
            f1.result(timeout=30),
            real_predict(np.zeros((2, 16), np.float32)))
        f2.result(timeout=30)
        snap = svc.metrics.snapshot()
    assert snap["retries"] == 2
    assert snap["requests"] == 2


def test_transient_failure_beyond_budget_fails_every_future():
    engine = _engine()

    def always_down(X):
        raise ConnectionError("engine unreachable")

    engine.predict = always_down
    with ServingService(engine, max_wait_ms=20.0, retries=1,
                        retry_backoff_ms=1.0) as svc:
        f = svc.submit(np.zeros((2, 16), np.float32))
        with pytest.raises(ConnectionError):
            f.result(timeout=30)
        assert svc.metrics.retries == 1  # budget spent, then fail fast


def test_permanent_engine_error_fails_fast_without_retry():
    """ValueError/TypeError (and anything not matching the transient
    markers) must not burn retry latency — same-batch redispatch can
    only fail identically."""
    engine = _engine()

    def broken(X):
        raise ValueError("shape mismatch inside the engine")

    engine.predict = broken
    with ServingService(engine, max_wait_ms=20.0, retries=3,
                        retry_backoff_ms=50.0) as svc:
        f = svc.submit(np.zeros((2, 16), np.float32))
        with pytest.raises(ValueError):
            f.result(timeout=30)
        assert svc.metrics.retries == 0


def test_retry_respects_request_deadline():
    """An always-transient engine + a short request deadline: the
    request resolves DeadlineExceeded (shed as 'deadline') rather than
    burning the full backoff schedule past its deadline — the retry
    loop caps each sleep at the earliest live deadline and sheds
    expired requests between attempts."""
    engine = _engine()

    def always_down(X):
        raise OSError("connection reset")

    engine.predict = always_down
    with ServingService(engine, max_wait_ms=1.0, retries=50,
                        retry_backoff_ms=40.0) as svc:
        t0 = time.perf_counter()
        f = svc.submit(np.zeros((2, 16), np.float32), timeout_s=0.15)
        with pytest.raises(DeadlineExceeded):
            f.result(timeout=30)
        # 50 x 40ms+ of blind backoff would be 2s+; the deadline cap
        # ends the episode within a few sleep quanta of the deadline
        assert time.perf_counter() - t0 < 1.5
    assert svc.metrics.shed_deadline == 1
    assert svc.metrics.retries >= 1


# -- registry surface -------------------------------------------------

def test_registry_exposes_serving():
    from fedamw_tpu import registry

    serving = registry.get_serving()
    assert serving.ServingEngine is ServingEngine
