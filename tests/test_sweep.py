"""sweep.py — the NNI-free twin of the config.yml tuning flow."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_sweep_runs_trials_and_writes_report(tmp_path):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "sweep.py"),
         "--dataset", "digits", "--trials", "2", "--round", "3",
         "--seed", "0", "--out", str(tmp_path / "TUNING.md")],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=600,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    report = (tmp_path / "TUNING.md").read_text()
    assert "| rank | lr_p | lambda_reg |" in report
    # two ranked data rows, accuracies parsed back as floats
    rows = [ln for ln in report.splitlines() if ln.startswith("| 1 |")
            or ln.startswith("| 2 |")]
    assert len(rows) == 2
    accs = [float(r.split("|")[4]) for r in rows]
    assert accs[0] >= accs[1]  # ranked by accuracy


def test_sweep_samples_from_reference_grid():
    import sweep

    for lp, lam in [(lp, lam) for lp in sweep.LR_P_GRID
                    for lam in sweep.LAMBDA_REG_GRID][:5]:
        assert lp in sweep.LR_P_GRID and lam in sweep.LAMBDA_REG_GRID
    # the grids mirror config.yml's search space values
    import yaml

    with open(os.path.join(REPO, "config.yml")) as f:
        cfg = yaml.safe_load(f)
    assert sweep.LR_P_GRID == cfg["searchSpace"]["lr_p"]["_value"]
    assert (sweep.LAMBDA_REG_GRID
            == cfg["searchSpace"]["lambda_reg"]["_value"])
