"""utils/telemetry.py — the unified telemetry plane (ISSUE 12).

Five layers, all tier-1:

- **Registry semantics**: typed instruments with label sets, idempotent
  re-request, kind conflicts refused, exactly-once counts under
  threaded increments (the thread-safety contract the serving worker
  and publisher threads lean on), ring-buffer wraparound keeping the
  NEWEST tail.
- **Windowed math**: counter rates and SLO attainment/burn-rate against
  hand-computed fixtures on an injected synthetic clock — the
  admission/autoscaling signal (ROADMAP direction 4) must be exact
  arithmetic, not vibes.
- **Exporters**: Prometheus text and OTLP-shaped JSON round-trips,
  including a REAL traced training run through ``tools/obs_export.py``
  (the acceptance criterion), and the serve-side per-class latency
  family driven by real ``ServingService`` traffic.
- **Device-time attribution**: the Chrome-trace parser against a
  synthetic capture with and without device lanes, and the graceful
  CPU fallback of a real ``jax.profiler`` probe (this suite runs on
  JAX_PLATFORMS=cpu, where the capture has no device lane by
  construction).
- **Trace-context propagation** (the DCN-hop satellite): inject/
  extract round-trips over both carrier spellings, malformed carriers
  loud.
"""

import glob
import gzip
import json
import os
import sys
import threading

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from fedamw_tpu.utils import telemetry as T  # noqa: E402
from fedamw_tpu.utils import trace as trace_mod  # noqa: E402

pytestmark = pytest.mark.telemetry


class FakeClock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t


@pytest.fixture()
def clock():
    return FakeClock()


@pytest.fixture()
def reg(clock):
    return T.Registry(clock=clock)


# -- registry semantics -----------------------------------------------

def test_instrument_identity_and_kind_conflicts(reg):
    a = reg.counter("reqs_total", "help", labels={"class": "x"})
    b = reg.counter("reqs_total", labels={"class": "x"})
    assert a is b  # idempotent: callers never need to cache children
    c = reg.counter("reqs_total", labels={"class": "y"})
    assert c is not a
    with pytest.raises(TypeError, match="one name, one type"):
        reg.gauge("reqs_total")
    with pytest.raises(ValueError, match="bad instrument name"):
        reg.counter("bad name")
    h = reg.histogram("lat", bounds=(0.1, 1.0))
    assert h.bounds == (0.1, 1.0)
    with pytest.raises(ValueError, match="different bounds"):
        reg.histogram("lat", labels={"class": "x"}, bounds=(0.5,))
    with pytest.raises(ValueError, match="cannot decrease"):
        a.inc(-1)


def test_counter_exactly_once_under_threaded_increments(reg):
    """The concurrency pin: N threads x M increments land exactly
    N*M — on the cumulative value AND on the retained series tail."""
    c = reg.counter("hits_total")
    n_threads, per = 8, 500

    def worker():
        for _ in range(per):
            c.inc()

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == n_threads * per
    # the ring holds the newest tail of CUMULATIVE values; the last
    # sample must equal the final count (no lost update anywhere)
    items = c.series.items()
    assert items[-1][1] == n_threads * per
    assert len(items) + c.series.dropped == n_threads * per


def test_ring_buffer_wraparound_keeps_newest(clock):
    ts = T.TimeSeries(capacity=4)
    for i in range(10):
        ts.append(float(i), float(i * 10))
    assert len(ts) == 4
    assert ts.dropped == 6
    assert ts.items() == [(6.0, 60.0), (7.0, 70.0), (8.0, 80.0),
                          (9.0, 90.0)]
    assert ts.window(8.0) == [(8.0, 80.0), (9.0, 90.0)]
    with pytest.raises(ValueError):
        T.TimeSeries(capacity=0)


def test_disabled_registry_keeps_values_skips_series(clock):
    reg = T.Registry(enabled=False, clock=clock)
    c = reg.counter("x_total")
    g = reg.gauge("g")
    h = reg.histogram("h")
    c.inc(3)
    g.set(7)
    h.observe(0.5)
    assert c.value == 3 and g.value == 7 and h.count == 1
    assert reg.points_recorded() == 0  # the cheap plane-off mode
    assert h.percentile(50) is None  # series-backed reads degrade


# -- windowed math ----------------------------------------------------

def test_counter_rate_hand_computed(reg, clock):
    c = reg.counter("ticks_total")
    for i in range(10):
        clock.t = float(i)  # one inc per second, t = 0..9
        c.inc()
    # window (4, 9]: cumulative went 5 -> 10 over 5s
    assert c.rate(5.0, now=9.0) == pytest.approx(1.0)
    # a wider window than the series' life: base is an honest zero
    assert c.rate(100.0, now=9.0) == pytest.approx(10 / 100.0)


def test_gauge_window_stats(reg, clock):
    g = reg.gauge("load")
    for i, v in enumerate((1.0, 5.0, 3.0)):
        clock.t = float(i)
        g.set(v)
    s = g.window_stats(10.0, now=2.0)
    assert s == {"n": 3, "min": 1.0, "mean": 3.0, "max": 5.0,
                 "last": 3.0}
    assert g.window_stats(0.5, now=10.0)["n"] == 0


def test_slo_attainment_and_burn_rate_hand_computed(reg, clock):
    """Fixture: 100 interactive requests in the last 50s, 10 of them
    over the 50ms threshold -> attainment 0.90, error rate 0.10,
    budget 0.01 (objective 0.99) -> burn rate 10.0 exactly."""
    h = reg.histogram("serve_request_latency_seconds",
                      labels={"class": "interactive"})
    for i in range(100):
        clock.t = 50.0 + i * 0.5  # t in [50, 99.5]
        h.observe(0.2 if i % 10 == 0 else 0.01)
    ev = T.SloEvaluator(
        reg, classes=(T.SloClass("interactive", threshold_ms=50.0,
                                 objective=0.99),),
        windows_s=(60.0, 20.0))
    out = ev.evaluate(now=100.0)
    w60 = out["classes"]["interactive"]["windows"]["60s"]
    assert w60["total"] == 100 and w60["good"] == 90
    assert w60["attainment"] == pytest.approx(0.9)
    assert w60["burn_rate"] == pytest.approx(10.0)
    # the 20s window holds samples with t >= 80: i in [60, 99], four
    # of which (60, 70, 80, 90) are slow -> 36/40 good
    w20 = out["classes"]["interactive"]["windows"]["20s"]
    assert w20["total"] == 40 and w20["good"] == 36
    assert w20["burn_rate"] == pytest.approx((4 / 40) / 0.01)


def test_slo_empty_window_is_no_data_not_perfect(reg):
    ev = T.SloEvaluator(reg, classes=(T.SloClass("batch", 500.0,
                                                 objective=0.95),))
    out = ev.evaluate(now=1000.0)
    w = out["classes"]["batch"]["windows"]["60s"]
    # no traffic must read as "no data" — an autoscaler seeing
    # attainment 1.0 on an idle class would never scale from zero
    assert w["total"] == 0
    assert w["attainment"] is None and w["burn_rate"] is None
    # and the pure read minted NO phantom family into the registry
    # (evaluate uses the non-creating lookup)
    assert reg.instruments() == []
    assert reg.lookup("serve_request_latency_seconds",
                      labels={"class": "batch"}) is None


def test_slo_class_validation():
    with pytest.raises(ValueError, match="objective"):
        T.SloClass("x", threshold_ms=50.0, objective=1.0)
    with pytest.raises(ValueError, match="threshold_ms"):
        T.SloClass("x", threshold_ms=0.0)
    with pytest.raises(ValueError, match="at least one"):
        T.SloEvaluator(T.Registry(), classes=())


# -- exporters --------------------------------------------------------

def _populated_registry(clock):
    reg = T.Registry(clock=clock)
    clock.t = 1.0
    reg.counter("reqs_total", "requests", labels={"class": "a"}).inc(5)
    reg.gauge("depth", "queue depth").set(3.0)
    h = reg.histogram("lat_seconds", "latency", bounds=(0.01, 0.1))
    h.observe(0.005)
    h.observe(0.05)
    h.observe(5.0)
    return reg


def test_prometheus_render_round_trip(clock):
    reg = _populated_registry(clock)
    text = T.render_prometheus(reg)
    assert "# TYPE reqs_total counter" in text
    assert "# HELP depth queue depth" in text
    parsed = T.parse_prometheus(text)
    assert parsed['reqs_total{class="a"}'] == 5.0
    assert parsed["depth"] == 3.0
    # histogram triplet with CUMULATIVE buckets and a +Inf tail
    assert parsed['lat_seconds_bucket{le="0.01"}'] == 1.0
    assert parsed['lat_seconds_bucket{le="0.1"}'] == 2.0
    assert parsed['lat_seconds_bucket{le="+Inf"}'] == 3.0
    assert parsed["lat_seconds_count"] == 3.0
    assert parsed["lat_seconds_sum"] == pytest.approx(5.055)
    # the dump dict renders identically (the offline CLI path)
    assert T.render_prometheus(reg.dump()) == text


def test_registry_otlp_shape_and_anchor(clock):
    reg = _populated_registry(clock)
    doc = T.registry_to_otlp(reg)
    metrics = {m["name"]: m
               for m in doc["resourceMetrics"][0]["scopeMetrics"][0]
               ["metrics"]}
    assert set(metrics) == {"reqs_total", "depth", "lat_seconds"}
    assert metrics["reqs_total"]["sum"]["isMonotonic"] is True
    pt = metrics["reqs_total"]["sum"]["dataPoints"][0]
    assert pt["asDouble"] == 5.0
    assert pt["attributes"] == [
        {"key": "class", "value": {"stringValue": "a"}}]
    # anchor mapping: sample at mono t=1.0, anchor captured at
    # clock()=0 when the registry was built -> unix_s + 1.0
    want_ns = int((reg.anchor["unix_s"] + 1.0) * 1e9)
    assert abs(int(pt["timeUnixNano"]) - want_ns) < 2
    hist = metrics["lat_seconds"]["histogram"]["dataPoints"][0]
    assert hist["count"] == "3"
    assert hist["bucketCounts"] == ["1", "1", "1"]
    assert hist["explicitBounds"] == [0.01, 0.1]


def test_non_finite_values_export_instead_of_crashing(clock):
    """A diverging run's loss gauge IS NaN; both exporters must render
    it (Prometheus literal NaN/+Inf; proto3-JSON string spellings) —
    a crash here would lose the run's results to its own telemetry."""
    reg = T.Registry(clock=clock)
    reg.gauge("loss").set(float("nan"))
    reg.gauge("ratio").set(float("inf"))
    text = T.render_prometheus(reg)
    parsed_lines = dict(ln.rsplit(None, 1) for ln in text.splitlines()
                        if not ln.startswith("#") and ln)
    assert parsed_lines["loss"] == "NaN"
    assert parsed_lines["ratio"] == "+Inf"
    doc = T.registry_to_otlp(reg)
    json.dumps(doc, allow_nan=False)  # strictly valid JSON
    pts = {m["name"]: m["gauge"]["dataPoints"][0]["asDouble"]
           for m in doc["resourceMetrics"][0]["scopeMetrics"][0]
           ["metrics"]}
    assert pts == {"loss": "NaN", "ratio": "Infinity"}
    span = {"name": "x", "kind": "span", "trace_id": "t-1",
            "span_id": "s-1", "parent_id": None, "start_s": 0.0,
            "dur_s": 0.1, "attrs": {"loss": float("nan")}}
    json.dumps(T.spans_to_otlp([span]), allow_nan=False)


def test_registry_otlp_merges_label_sets_per_family(clock):
    reg = T.Registry(clock=clock)
    reg.counter("reqs_total", labels={"class": "a"}).inc(1)
    reg.counter("reqs_total", labels={"class": "b"}).inc(2)
    doc = T.registry_to_otlp(reg)
    metrics = doc["resourceMetrics"][0]["scopeMetrics"][0]["metrics"]
    # ONE metric entry per family; the label sets become dataPoints
    assert [m["name"] for m in metrics] == ["reqs_total"]
    pts = metrics[0]["sum"]["dataPoints"]
    got = {pt["attributes"][0]["value"]["stringValue"]: pt["asDouble"]
           for pt in pts}
    assert got == {"a": 1.0, "b": 2.0}


def test_spans_otlp_ids_and_parenting():
    spans = [
        {"name": "request", "kind": "span", "trace_id": "req-7",
         "span_id": "s-1", "parent_id": None, "start_s": 10.0,
         "dur_s": 0.5, "attrs": {"rows": 4, "ok": True, "q": 1.5}},
        {"name": "engine_retry", "kind": "annotation",
         "trace_id": "req-7", "span_id": "s-2", "parent_id": "s-1",
         "start_s": 10.2, "dur_s": 0.0, "attrs": {}},
    ]
    doc = T.spans_to_otlp(spans, anchor={"unix_s": 100.0,
                                         "mono_s": 0.0})
    out = doc["resourceSpans"][0]["scopeSpans"][0]["spans"]
    assert len(out) == 2
    root, note = out
    assert len(root["traceId"]) == 32 and len(root["spanId"]) == 16
    assert root["traceId"] == note["traceId"]  # same trace, same id
    assert note["parentSpanId"] == root["spanId"]  # hashed parenting
    assert root["startTimeUnixNano"] == str(int(110.0 * 1e9))
    assert root["endTimeUnixNano"] == str(int(110.5 * 1e9))
    attrs = {a["key"]: a["value"] for a in root["attributes"]}
    assert attrs["rows"] == {"intValue": "4"}
    assert attrs["ok"] == {"boolValue": True}
    assert attrs["q"] == {"doubleValue": 1.5}
    assert attrs["trace_id_raw"] == {"stringValue": "req-7"}
    note_attrs = {a["key"]: a["value"] for a in note["attributes"]}
    assert note_attrs["kind_raw"] == {"stringValue": "annotation"}


def test_obs_export_cli_round_trips_a_real_traced_run(tmp_path):
    """The acceptance criterion: a REAL traced (and telemetered)
    training run exports through tools/obs_export.py and every span
    id survives the OTLP conversion exactly once."""
    from fedamw_tpu.algorithms import FedAvg, prepare_setup
    from fedamw_tpu.data import FederatedDataset, dirichlet_partition
    from fedamw_tpu.data.synthetic import synthetic_classification

    import tools.obs_export as ox

    X, y, Xt, yt = synthetic_classification(256, 8, 2, seed=3)
    parts, _ = dirichlet_partition(y, 4, alpha=0.5, seed=2020,
                                   min_size=0)
    ds = FederatedDataset(
        name="tel", task_type="classification", num_classes=2, d=8,
        X_train=X, y_train=y, X_test=Xt, y_test=yt, parts=parts,
        source="synthetic")
    setup = prepare_setup(ds, D=16, kernel_par=0.1, seed=100,
                          rng=np.random.RandomState(100))
    rounds = 3
    tracer = trace_mod.configure()
    registry = T.reset_registry()
    try:
        FedAvg(setup, lr=0.5, epoch=1, batch_size=32, round=rounds,
               seed=0, lr_mode="constant")
    finally:
        trace_mod.configure(enabled=False)
    trace_path = str(tmp_path / "run_trace.jsonl")
    n_spans = tracer.export_jsonl(trace_path)
    assert n_spans >= rounds + 1  # the scan span + one per round
    dump_path = str(tmp_path / "run_telemetry.json")
    with open(dump_path, "w") as f:
        json.dump(registry.dump(), f)
    out_path = str(tmp_path / "run_otlp.json")
    assert ox.main([trace_path, dump_path, "-o", out_path]) == 0
    with open(out_path) as f:
        doc = json.load(f)
    otlp_spans = doc["resourceSpans"][0]["scopeSpans"][0]["spans"]
    raw_ids = [
        next(a["value"]["stringValue"] for a in s["attributes"]
             if a["key"] == "id_raw")
        for s in otlp_spans]
    want_ids = [r["span_id"] for r in tracer.records()]
    assert sorted(raw_ids) == sorted(want_ids)  # exactly once, all
    # round spans hang under the scan span after id hashing
    by_name = {}
    for s in otlp_spans:
        by_name.setdefault(s["name"], []).append(s)
    scan = by_name["train_scan"][0]
    assert all(r["parentSpanId"] == scan["spanId"]
               for r in by_name["round"])
    # the telemetry side came through with the per-round loss series
    names = {m["name"]
             for m in doc["resourceMetrics"][0]["scopeMetrics"][0]
             ["metrics"]}
    assert {"fed_train_loss", "fed_test_acc"} <= names
    # header anchor -> unix-epoch timeline (not the monotonic raw)
    assert int(otlp_spans[0]["startTimeUnixNano"]) > 10**17
    # prometheus mode renders the registry and refuses the trace
    assert ox.main([dump_path, "--format", "prometheus",
                    "-o", str(tmp_path / "m.prom")]) == 0
    assert "fed_train_loss" in (tmp_path / "m.prom").read_text()
    assert ox.main([trace_path, "--format", "prometheus"]) == 1


def test_serve_metrics_slo_family_via_real_service():
    """The serving wire-up: slo_class on submit lands the request in
    the labeled latency family, and ServeMetrics.slo() evaluates it."""
    from fedamw_tpu.serving import ServeMetrics, ServingEngine, \
        ServingService

    eng = ServingEngine({"w": np.zeros((2, 8), np.float32)},
                        buckets=(1, 4))
    eng.warmup()
    m = ServeMetrics()
    with ServingService(eng, metrics=m) as svc:
        for i in range(10):
            svc.submit(np.zeros(8, np.float32),
                       slo_class="interactive" if i % 2 else "batch"
                       ).result(timeout=30)
    slo = m.slo(windows_s=(300.0,))
    tot = {k: v["windows"]["300s"]["total"]
           for k, v in slo["classes"].items()}
    assert tot == {"interactive": 5, "batch": 5}
    snap = m.snapshot(eng)
    assert snap["requests"] == 10
    assert snap["latency_seen"] == 10
    assert snap["reservoir_degraded"] is False
    assert snap["device_attribution"] is None
    # the registry carries the re-based counters as series
    assert m.registry.snapshot()["serve_requests_total"] == 10.0


def test_latency_histogram_reservoir_honesty():
    from fedamw_tpu.serving import LatencyHistogram

    h = LatencyHistogram(max_samples=10)
    for i in range(10):
        h.record(0.001 * (i + 1))
    assert h.accounting() == {"seen": 10, "sampled": 10,
                              "reservoir_degraded": False}
    h.record(0.5)
    acct = h.accounting()
    assert acct == {"seen": 11, "sampled": 10,
                    "reservoir_degraded": True}
    assert h.count == 11 and h.sampled == 10 and h.degraded is True


# -- device-time attribution ------------------------------------------

def _write_capture(tmp_path, events):
    d = tmp_path / "plugins" / "profile" / "2026_01_01"
    d.mkdir(parents=True)
    with gzip.open(str(d / "host.trace.json.gz"), "wt") as f:
        json.dump({"traceEvents": events}, f)
    return str(tmp_path)


def test_parse_profiler_trace_device_lanes(tmp_path):
    events = [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        {"ph": "M", "pid": 2, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "X", "pid": 1, "name": "PjitFunction", "dur": 500.0},
        {"ph": "X", "pid": 2, "name": "fusion.1", "dur": 120.0},
        {"ph": "X", "pid": 2, "name": "fusion.2", "dur": 80.0},
    ]
    parsed = T.parse_profiler_trace(_write_capture(tmp_path, events))
    # only the device lane counts: 200us of op time, host excluded
    assert parsed == {"device_busy_s": pytest.approx(200e-6),
                      "device_events": 2, "device_lanes": 1}


def test_parse_profiler_trace_host_only_is_none(tmp_path):
    events = [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        {"ph": "X", "pid": 1, "name": "TfrtCpuExecutable::Execute",
         "dur": 300.0},
    ]
    assert T.parse_profiler_trace(
        _write_capture(tmp_path, events)) is None
    assert T.parse_profiler_trace(str(tmp_path / "empty")) is None


def test_attribute_device_time_cpu_fallback_real_profiler():
    """The tested graceful fallback: a REAL jax.profiler capture on
    the CPU backend yields no device lane, and attribution says so
    instead of guessing."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: (x @ x.T).sum())
    x = jnp.ones((32, 32))
    f(x).block_until_ready()

    import time as _time

    def dispatch():
        t0 = _time.perf_counter()
        f(x).block_until_ready()
        return _time.perf_counter() - t0

    attr = T.attribute_device_time(dispatch, reps=2)
    assert attr["source"] == "none"
    assert "no device lane" in attr["reason"]
    assert attr["dispatch_s"] > 0
    assert attr["reps"] == 2


def test_attribute_device_time_profiler_failure_degrades():
    def dispatch():
        raise RuntimeError("synthetic dispatch failure")

    attr = T.attribute_device_time(dispatch, reps=1)
    assert attr["source"] == "none"
    assert "RuntimeError" in attr["reason"]


def test_metrics_device_split_from_profiler_attribution():
    """With a profiler-sourced attribution installed, the snapshot's
    device family grows the compute/queue split at the measured
    fraction — and without one, the split keys are absent."""
    from fedamw_tpu.serving import ServeMetrics

    m = ServeMetrics()
    m.record_batch(n_requests=2, n_rows=2, latencies=[0.01, 0.02],
                   stage_seconds={"queue": [0.001, 0.001],
                                  "pad": 0.002, "device": 0.008})
    snap = m.snapshot()
    assert "device_compute_p50_ms" not in snap
    m.install_device_attribution({
        "source": "profiler", "compute_fraction": 0.75,
        "device_compute_s": 0.06, "xla_queue_s": 0.02})
    snap = m.snapshot()
    assert snap["device_attribution"]["source"] == "profiler"
    assert snap["device_compute_p50_ms"] == pytest.approx(
        snap["device_p50_ms"] * 0.75, rel=1e-6)
    assert snap["xla_queue_p50_ms"] == pytest.approx(
        snap["device_p50_ms"] * 0.25, rel=1e-6)


# -- trace-context propagation ----------------------------------------

def test_trace_context_round_trip_dict_and_header():
    carrier = trace_mod.inject_context("req-42", span_id="s-7")
    assert carrier == {"schema": "TRACECTX.v1", "trace_id": "req-42",
                      "parent_id": "s-7"}
    json.dumps(carrier)  # serializable by construction
    ctx = trace_mod.extract_context(carrier)
    assert ctx.trace_id == "req-42" and ctx.parent_id == "s-7"
    header = trace_mod.format_context(carrier)
    assert header == "TRACECTX.v1;req-42;s-7"
    assert trace_mod.extract_context(header) == ctx
    # rootless carrier (no current span): parent collapses to None
    root = trace_mod.inject_context("req-9")
    assert trace_mod.extract_context(
        trace_mod.format_context(root)).parent_id is None


def test_trace_context_remote_side_lands_one_trace():
    """The DCN-hop shape: the remote process emits its span under the
    extracted context, and both sides share one trace id."""
    local = trace_mod.Tracer()
    rid = local.new_id("req")
    with local.span("dispatch", rid) as sp:
        pass
    carrier = trace_mod.format_context(
        trace_mod.inject_context(rid, span_id=sp.span_id))
    remote = trace_mod.Tracer()  # a different process's tracer
    ctx = trace_mod.extract_context(carrier)
    with remote.span("remote_serve", ctx.trace_id,
                     parent_id=ctx.parent_id):
        pass
    rec = remote.records()[0]
    assert rec["trace_id"] == rid
    assert rec["parent_id"] == sp.span_id


def test_trace_context_malformed_is_loud():
    for bad in ("TRACECTX.v1;only-two", "WRONG.v1;a;b", "", "a;b;c;d",
                {"schema": "TRACECTX.v1"}, {"schema": "nope"}, 42):
        with pytest.raises(ValueError):
            trace_mod.extract_context(bad)
    with pytest.raises(ValueError):
        trace_mod.inject_context("")
    with pytest.raises(ValueError):
        trace_mod.inject_context("has;separator")


def test_export_header_carries_wall_anchor(tmp_path):
    tr = trace_mod.Tracer()
    tr.emit("x", tr.new_id("t"), 1.0, 0.1)
    path = str(tmp_path / "t.jsonl")
    tr.export_jsonl(path)
    header, spans = trace_mod.read_jsonl(path)
    assert header["anchor_unix_s"] > 10**9  # wall clock, header-only
    assert header["anchor_mono_s"] >= 0
    assert all("anchor_unix_s" not in s for s in spans)
