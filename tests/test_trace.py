"""The trace core (utils/trace.py) and its wiring (ISSUE 5).

Pins: span lifecycle (context manager, explicit emit, annotations,
parenting); the disabled-mode zero-allocation path (span() returns ONE
shared no-op object and emit records nothing); the JSONL schema
round-trip (export -> read_jsonl is lossless for every SPAN_FIELDS
key); thread-safety under concurrent emitters AND under concurrent
ServingService.submit (every request id lands exactly one "request"
span, the serving-side acceptance contract); the bounded collector's
drop accounting; and the training-side emission — a FedAvg run with
the global tracer configured emits one train_scan span plus one round
record per round with the fault counters attached as attributes.
"""

import json
import threading

import numpy as np
import pytest

from fedamw_tpu.utils import reporting
from fedamw_tpu.utils import trace as trace_mod
from fedamw_tpu.utils.trace import (NULL_TRACER, SPAN_FIELDS,
                                    TRACE_SCHEMA, Tracer, read_jsonl)


# -- span lifecycle ---------------------------------------------------

def test_span_context_manager_records_duration_and_attrs():
    tr = Tracer()
    with tr.span("stage", "req-1", color="blue") as sp:
        pass
    assert sp.span_id is not None
    (rec,) = tr.records()
    assert rec["name"] == "stage"
    assert rec["kind"] == "span"
    assert rec["trace_id"] == "req-1"
    assert rec["span_id"] == sp.span_id
    assert rec["dur_s"] >= 0
    assert rec["attrs"] == {"color": "blue"}


def test_span_records_on_exception_and_reraises():
    tr = Tracer()
    with pytest.raises(ValueError):
        with tr.span("failing", "req-1"):
            raise ValueError("boom")
    (rec,) = tr.records()
    assert rec["attrs"]["error"] == "ValueError"


def test_emit_parenting_and_annotations():
    tr = Tracer()
    parent = tr.emit("train_scan", "run-1", 0.0, 2.0, rounds=2)
    tr.emit("round", "run-1", 0.0, 1.0, parent_id=parent, round=0)
    tr.annotate("retry", "run-1", parent_id=parent, attempt=1)
    scan, rnd, note = tr.records()
    assert rnd["parent_id"] == scan["span_id"] == parent
    assert note["kind"] == "annotation" and note["dur_s"] == 0.0
    assert note["attrs"] == {"attempt": 1}


def test_emit_attrs_dict_and_kwargs_spellings_merge():
    tr = Tracer()
    tr.emit("s", "t", 0.0, 1.0, attrs={"a": 1, "b": 1}, b=2)
    (rec,) = tr.records()
    assert rec["attrs"] == {"a": 1, "b": 2}  # kw wins on clash


def test_new_ids_are_unique_and_prefixed():
    tr = Tracer()
    ids = [tr.new_id("req") for _ in range(100)]
    assert len(set(ids)) == 100
    assert all(i.startswith("req-") for i in ids)


# -- disabled mode ----------------------------------------------------

def test_disabled_span_is_one_shared_noop_object():
    tr = Tracer(enabled=False)
    spans = {id(tr.span("a", "t")) for _ in range(32)}
    spans |= {id(NULL_TRACER.span("b", "t"))}
    # the zero-allocation path: every call hands back the SAME object
    assert len(spans) == 1
    with tr.span("a", "t"):
        pass
    assert len(tr) == 0


def test_disabled_emit_and_annotate_record_nothing():
    tr = Tracer(enabled=False)
    assert tr.emit("s", "t", 0.0, 1.0) is None
    assert tr.annotate("n", "t") is None
    assert tr.records() == [] and tr.dropped == 0


# -- bounded collector ------------------------------------------------

def test_collector_bound_drops_and_counts():
    tr = Tracer(max_spans=3)
    kept = [tr.emit("s", f"t{i}", 0.0, 1.0) for i in range(5)]
    assert len(tr) == 3 and tr.dropped == 2
    assert kept[3] is None and kept[4] is None  # dropped -> no id
    with pytest.raises(ValueError):
        Tracer(max_spans=0)


# -- JSONL round-trip -------------------------------------------------

def test_jsonl_schema_round_trip(tmp_path):
    tr = Tracer()
    parent = tr.emit("train_scan", "run-1", 1.5, 2.5, rounds=3)
    tr.emit("round", "run-1", 1.5, 0.5, parent_id=parent,
            round=0, test_acc=97.5)
    tr.annotate("retry", "run-1", attempt=2)
    path = str(tmp_path / "trace.jsonl")
    assert tr.export_jsonl(path) == 3
    header, spans = read_jsonl(path)
    assert header["schema"] == TRACE_SCHEMA
    assert header["spans"] == 3 and header["dropped"] == 0
    originals = tr.records()
    assert len(spans) == len(originals)
    for got, want in zip(spans, originals):
        assert set(got) == set(SPAN_FIELDS)
        for k in SPAN_FIELDS:
            assert got[k] == want[k], k
    # a non-trace file is rejected loudly, not half-parsed
    other = tmp_path / "not_trace.jsonl"
    other.write_text(json.dumps({"schema": "BENCH_SERVE.v1"}) + "\n")
    with pytest.raises(ValueError, match="TRACE"):
        read_jsonl(str(other))


# -- streaming (rotating JSONL writer) --------------------------------

def test_streaming_writer_rotates_and_keeps_collector_empty(tmp_path):
    """The long-lived-loop mode (ISSUE 6 satellite): spans stream to
    rotating part files — each standalone-readable with the schema
    header, nothing lost at rotation boundaries — while the in-memory
    collector stays EMPTY (the unbounded-growth fix)."""
    from fedamw_tpu.utils.trace import RotatingJsonlWriter

    w = RotatingJsonlWriter(str(tmp_path / "stream"),
                            max_spans_per_file=10)
    tr = Tracer(writer=w)
    ids = [tr.emit("request", f"req-{i}", float(i), 0.1, outcome="ok")
           for i in range(25)]
    assert all(ids)  # streaming spans still get ids
    assert len(tr) == 0 and tr.dropped == 0  # nothing buffered
    # per-span flush: a tailing shipper (or a crash) sees every span
    # already on disk BEFORE close
    _, live_spans = read_jsonl(w.paths[-1])
    assert len(live_spans) == 5
    w.close()
    assert w.spans_written == 25
    assert len(w.paths) == 3  # 10 + 10 + 5
    seen = []
    for path in w.paths:
        header, spans = read_jsonl(path)
        assert header["schema"] == TRACE_SCHEMA
        assert header["streaming"] is True
        assert len(spans) <= 10
        for s in spans:
            assert set(s) == set(SPAN_FIELDS)
        seen += [s["trace_id"] for s in spans]
    assert seen == [f"req-{i}" for i in range(25)]  # exactly once, ordered
    # a streaming tracer refuses the buffered-export spelling (the
    # spans are already on disk; silently writing 0 would look green)
    with pytest.raises(ValueError, match="streaming"):
        tr.export_jsonl(str(tmp_path / "nope.jsonl"))
    # writing after close is loud, not a silent drop
    with pytest.raises(ValueError, match="closed"):
        w.write(dict(zip(SPAN_FIELDS, ["n", "span", "t", "s", None,
                                       0.0, 0.0, {}])))
    # even when closed BEFORE the first span (lazy open must not
    # silently resurrect a closed writer)
    w2 = RotatingJsonlWriter(str(tmp_path / "early"))
    w2.close()
    with pytest.raises(ValueError, match="closed"):
        w2.write(dict(zip(SPAN_FIELDS, ["n", "span", "t", "s", None,
                                        0.0, 0.0, {}])))
    assert w2.paths == []
    # a SUPERSEDED tracer (writer closed by a reconfigure while some
    # thread still holds it) degrades to counted drops, never raises
    # into the emitting thread
    assert tr.emit("late", "t-late", 0.0, 1.0) is None
    assert tr.dropped == 1


def test_streaming_writer_restart_never_truncates_prior_parts(tmp_path):
    """The crash-restart case the per-span flush exists for: a new
    writer pointed at a directory holding a previous run's parts must
    number PAST them, never reopen (and truncate) part 1."""
    from fedamw_tpu.utils.trace import RotatingJsonlWriter

    w1 = RotatingJsonlWriter(str(tmp_path), max_spans_per_file=5)
    t1 = Tracer(writer=w1)
    for i in range(7):
        t1.emit("s", f"run1-{i}", 0.0, 1.0)
    # no close(): simulate an OOM-killed process (flush already wrote)
    w2 = RotatingJsonlWriter(str(tmp_path), max_spans_per_file=5)
    t2 = Tracer(writer=w2)
    t2.emit("s", "run2-0", 0.0, 1.0)
    w2.close()
    assert not (set(w1.paths) & set(w2.paths))
    _, first_run_spans = read_jsonl(w1.paths[0])
    assert [s["trace_id"] for s in first_run_spans] == \
        [f"run1-{i}" for i in range(5)]  # prior run intact
    _, new_spans = read_jsonl(w2.paths[0])
    assert new_spans[0]["trace_id"] == "run2-0"


def test_streaming_writer_concurrent_writes_lose_nothing(tmp_path):
    from fedamw_tpu.utils.trace import RotatingJsonlWriter

    w = RotatingJsonlWriter(str(tmp_path), max_spans_per_file=50)
    tr = Tracer(writer=w)
    n_threads, per = 8, 100

    def emit(k):
        for i in range(per):
            tr.emit("s", f"t{k}-{i}", 0.0, 1.0)

    threads = [threading.Thread(target=emit, args=(k,))
               for k in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    w.close()
    assert w.spans_written == n_threads * per
    all_ids = []
    for path in w.paths:
        _, spans = read_jsonl(path)
        all_ids += [s["trace_id"] for s in spans]
    assert len(all_ids) == n_threads * per
    assert len(set(all_ids)) == n_threads * per  # exactly once each


# -- thread-safety ----------------------------------------------------

def test_concurrent_emitters_lose_nothing():
    tr = Tracer()
    n_threads, per = 8, 200

    def emitter(k):
        for i in range(per):
            tr.emit("s", f"t{k}-{i}", 0.0, 1.0)

    threads = [threading.Thread(target=emitter, args=(k,))
               for k in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    recs = tr.records()
    assert len(recs) == n_threads * per
    span_ids = [r["span_id"] for r in recs]
    assert len(set(span_ids)) == len(span_ids)


def test_concurrent_service_submit_traces_each_request_once():
    """The serving-side acceptance contract: under concurrent submit
    from many threads, every accepted request id appears EXACTLY once
    as a "request" span in the trace."""
    from fedamw_tpu.serving import ServingEngine, ServingService

    rng = np.random.RandomState(3)
    engine = ServingEngine({"w": rng.randn(2, 16).astype(np.float32)},
                           buckets=(8, 64))
    engine.warmup()
    tr = Tracer()
    n_threads, per = 6, 10
    submitted: list = []
    lock = threading.Lock()
    with ServingService(engine, max_wait_ms=1.0, tracer=tr) as svc:
        def client(k):
            rng_k = np.random.RandomState(k)
            for _ in range(per):
                fut = svc.submit(
                    rng_k.randn(2, 16).astype(np.float32))
                with lock:
                    submitted.append(fut.request_id)
                fut.result(timeout=30)

        threads = [threading.Thread(target=client, args=(k,))
                   for k in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    req_spans = [r for r in tr.records() if r["name"] == "request"]
    ids = [r["trace_id"] for r in req_spans]
    assert sorted(ids) == sorted(submitted)
    assert len(set(ids)) == len(ids) == n_threads * per
    assert all(r["attrs"]["outcome"] == "ok" for r in req_spans)
    # the stage split is present on every served request
    for r in req_spans:
        for k in ("queue_ms", "pad_ms", "device_ms"):
            assert r["attrs"][k] >= 0


# -- reporting --------------------------------------------------------

def test_trace_summary_aggregates_per_stage():
    tr = Tracer()
    for d in (0.010, 0.020, 0.030):
        tr.emit("queue", "r", 0.0, d)
    tr.emit("device", "r", 0.0, 0.5)
    tr.annotate("retry", "r")
    s = reporting.trace_stage_summary(tr.records())
    assert s["stages"]["queue"]["count"] == 3
    assert s["stages"]["queue"]["p50_ms"] == pytest.approx(20.0)
    assert s["stages"]["device"]["total_s"] == pytest.approx(0.5)
    assert s["annotations"] == {"retry": 1}
    text = reporting.format_trace_summary("unit", tr.records())
    assert "device" in text and "! retry: 1" in text
    # device is the costliest stage -> reads first
    assert text.index("device") < text.index("queue")
    assert reporting.format_trace_summary("empty", []).endswith(
        "no spans recorded")


# -- global tracer + training-side emission ---------------------------

def test_configure_swaps_global_tracer():
    assert trace_mod.get_tracer() is NULL_TRACER
    try:
        tr = trace_mod.configure()
        assert trace_mod.get_tracer() is tr and tr.enabled
    finally:
        trace_mod.configure(enabled=False)
    assert trace_mod.get_tracer() is NULL_TRACER


def test_round_based_emits_scan_and_round_spans():
    """algorithms.core._round_based: with the global tracer enabled, a
    faulted FedAvg run emits one host-timed train_scan span plus one
    round record per round, parented to it, carrying the per-round
    metric stream and the fault counters as attributes."""
    from fedamw_tpu.algorithms import FedAvg, prepare_setup
    from fedamw_tpu.data import FederatedDataset, dirichlet_partition
    from fedamw_tpu.data.synthetic import synthetic_classification

    X, y, Xt, yt = synthetic_classification(256, 16, 2, seed=0)
    parts, _ = dirichlet_partition(y, 4, alpha=0.5, seed=1, min_size=0)
    ds = FederatedDataset(
        name="trace-synth", task_type="classification", num_classes=2,
        d=16, X_train=X, y_train=y, X_test=Xt, y_test=yt, parts=parts,
        source="synthetic")
    setup = prepare_setup(ds, D=32, kernel_par=0.1, seed=0,
                          rng=np.random.RandomState(0))
    rounds = 3
    try:
        tr = trace_mod.configure()
        res = FedAvg(setup, lr=0.5, epoch=1, batch_size=32,
                     round=rounds, seed=0, lr_mode="constant",
                     faults="drop=0.5,seed=3")
    finally:
        trace_mod.configure(enabled=False)
    recs = tr.records()
    scans = [r for r in recs if r["name"] == "train_scan"]
    rnds = [r for r in recs if r["name"] == "round"]
    assert len(scans) == 1 and len(rnds) == rounds
    scan = scans[0]
    assert scan["attrs"]["rounds"] == rounds
    assert scan["attrs"]["faults"] is True
    assert scan["dur_s"] > 0
    total_dropped = 0
    for i, r in enumerate(rnds):
        assert r["parent_id"] == scan["span_id"]
        assert r["trace_id"] == scan["trace_id"]
        assert r["attrs"]["round"] == i
        assert r["attrs"]["timing"] == "uniform"  # fused scan: no
        # host-visible round boundary, and the record says so
        assert r["attrs"]["test_acc"] == pytest.approx(
            float(res["test_acc"][i]))
        total_dropped += r["attrs"]["dropped"]
    assert total_dropped == int(
        np.asarray(res["fault_counts"]["dropped"]).sum())


def test_round_based_untraced_emits_nothing():
    """The default path stays span-free (the global tracer is the
    NULL tracer unless exp.py --trace_dir configured it)."""
    assert trace_mod.get_tracer() is NULL_TRACER
    assert len(NULL_TRACER) == 0
