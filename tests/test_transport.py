"""Cross-process dispatch transport (ISSUE 15).

Load-bearing contracts:

- **Frame protocol**: round-trips exactly; truncated, oversized, and
  garbage frames are rejected LOUDLY (typed ``FrameError`` — a
  permanent ``ValueError`` the retry machinery refuses to retry),
  never silently skipped or length-interpreted.
- **InProcess equivalence**: a ``Replica`` built without a transport
  dispatches byte-identically to a direct ``engine.predict`` — the
  extracted seam changes NOTHING in-process (every pre-existing
  replica/chaos/control test is the wider pin; these are the direct
  ones).
- **Deadline budget crosses the hop**: the dispatch frame carries the
  REMAINING budget (shrunk by time already spent), socket timeouts
  derive from it, an exhausted budget fails before any I/O, and the
  worker refuses expired work.
- **TRACECTX propagation**: the worker's ``pod_dispatch`` span lands
  under the exact trace id + parent the client injected —
  ``utils.trace.inject_context``'s consumer, end-to-end over a real
  socket.
- **NetChaosSpec determinism**: same spec ⇒ bitwise-identical
  schedule (the ``ChaosSpec``/``LoadSpec`` contract on the network
  axis); the grammar parses and validates loudly.
- **SIGKILL-mid-batch requeue**: a worker PROCESS killed mid-dispatch
  fails transiently; the router requeues the in-flight batch against
  a survivor within the original request deadline — nothing lost.
- **Worker version agreement**: one ``swap_weights`` announce lands
  every pod worker on the SAME version number; post-swap dispatches
  report it from the wire.
"""

import multiprocessing
import os
import signal
import socket
import threading
import time

import numpy as np
import pytest

from fedamw_tpu.serving import (FailoverRouter, FrameError,
                                InProcessTransport, NetChaosPlan,
                                NetChaosSpec, PodClientEngine,
                                PodWorker, Replica, ServingEngine,
                                ServingService, SocketTransport,
                                SyncTimeout, TransportError,
                                TransportRefused, TransportTimeout,
                                pack_weights, resolve_net_chaos,
                                unpack_weights, weights_fingerprint)
from fedamw_tpu.serving.chaos import (NET_CLEAN, NET_LAG,
                                      NET_PARTITION, NET_REFUSE)
from fedamw_tpu.serving.transport import (FRAME_MAGIC, pack_batch,
                                          read_frame, unpack_batch,
                                          write_frame)
from fedamw_tpu.utils.trace import Tracer, inject_context

pytestmark = pytest.mark.transport

D, C = 16, 3


class StubEngine:
    """Numpy-only engine for socket tests: deterministic logits, the
    metadata surface a PodWorker/facade needs, optional per-dispatch
    sleep (the slow worker the SIGKILL and timeout tests need)."""

    def __init__(self, sleep_s=0.0, seed=1, buckets=(1, 8, 32)):
        self.W = np.random.RandomState(seed).randn(C, D).astype(
            np.float32)
        self.buckets = tuple(buckets)
        self.input_dim = D
        self.num_classes = C
        self.version = 0
        self.compile_count = 0
        self.sleep_s = sleep_s

    def predict(self, X, version=None, record_timings=True):
        if self.sleep_s:
            time.sleep(self.sleep_s)
        return np.asarray(X, np.float32) @ self.W.T

    def swap_weights(self, params, rff=None, version=None):
        self.W = np.asarray(params["w"], np.float32)
        self.version = int(version)
        return self.version


def make_engine(buckets=(1, 8, 32)):
    rng = np.random.RandomState(1)
    e = ServingEngine({"w": rng.randn(C, D).astype(np.float32)},
                      buckets=buckets)
    e.warmup()
    return e


def rows(n, seed=5):
    return np.random.RandomState(seed).randn(n, D).astype(np.float32)


# -- frame protocol ----------------------------------------------------

def _pair():
    a, b = socket.socketpair()
    a.settimeout(5.0)
    b.settimeout(5.0)
    return a, b


def test_frame_round_trip_header_and_payload():
    a, b = _pair()
    try:
        X = rows(4)
        hdr, payload = pack_batch(X)
        hdr["kind"] = "dispatch"
        write_frame(a, hdr, payload)
        got, body = read_frame(b)
        assert got["kind"] == "dispatch"
        back = unpack_batch(got, body)
        assert np.array_equal(back, X)
        assert back.dtype == X.dtype
        # empty-payload frames round-trip too (control frames)
        write_frame(a, {"kind": "ping"})
        got2, body2 = read_frame(b)
        assert got2["kind"] == "ping" and body2 == b""
    finally:
        a.close()
        b.close()


def test_truncated_frame_rejected_loudly():
    a, b = _pair()
    try:
        X = rows(4)
        hdr, payload = pack_batch(X)
        hdr["kind"] = "dispatch"
        # capture the wire bytes, then replay a truncated prefix of
        # them: the reader must name the truncation, typed
        cap_a, cap_b = _pair()
        write_frame(cap_a, hdr, payload)
        cap_a.shutdown(socket.SHUT_WR)
        wire = b""
        while True:
            chunk = cap_b.recv(1 << 20)
            if not chunk:
                break
            wire += chunk
        cap_a.close()
        cap_b.close()
        a.sendall(wire[: len(wire) - 7])
        a.shutdown(socket.SHUT_WR)
        with pytest.raises(FrameError, match="truncated"):
            read_frame(b)
    finally:
        a.close()
        b.close()


def test_garbage_magic_rejected_loudly():
    a, b = _pair()
    try:
        a.sendall(b"GET / HTTP/1.1\r\n\r\n" + b"\x00" * 16)
        with pytest.raises(FrameError, match="magic"):
            read_frame(b)
    finally:
        a.close()
        b.close()


def test_oversized_frame_rejected_both_sides():
    a, b = _pair()
    try:
        # sender-side: the bound trips in the CALLER's stack
        with pytest.raises(FrameError, match="bound"):
            write_frame(a, {"kind": "dispatch"}, b"x" * 2048,
                        max_frame_bytes=1024)
        # receiver-side: a hostile/corrupt length prefix must not
        # allocate; it must raise before reading the body
        import struct
        a.sendall(struct.pack("!4sII", FRAME_MAGIC, 10, 1 << 30))
        with pytest.raises(FrameError, match="bound"):
            read_frame(b)
    finally:
        a.close()
        b.close()


def test_clean_eof_is_transient_not_frame_error():
    # a peer closing BETWEEN frames is ordinary worker death — the
    # transient family, which the failover machinery retries
    a, b = _pair()
    a.close()
    try:
        with pytest.raises(TransportError):
            read_frame(b)
    finally:
        b.close()


def test_unpack_batch_size_disagreement_is_loud():
    hdr, payload = pack_batch(rows(4))
    bad = dict(hdr, rows=5)
    with pytest.raises(FrameError, match="disagrees"):
        unpack_batch(bad, payload)


def test_weights_pack_round_trip():
    params = {"w": rows(3), "b": np.arange(3, dtype=np.float32)}
    rff = (rows(2, seed=9), np.arange(D, dtype=np.float32))
    p2, r2 = unpack_weights(pack_weights(params, rff))
    assert set(p2) == {"w", "b"}
    assert np.array_equal(p2["w"], params["w"])
    assert np.array_equal(r2[0], rff[0])
    p3, r3 = unpack_weights(pack_weights(params))
    assert r3 is None and np.array_equal(p3["b"], params["b"])
    with pytest.raises(FrameError):
        unpack_weights(b"not an npz")


# -- InProcessTransport equivalence -----------------------------------

def test_replica_default_transport_is_in_process_and_equivalent():
    engine = make_engine()
    rep = Replica(0, engine)
    assert isinstance(rep.transport, InProcessTransport)
    X = rows(6)
    assert np.array_equal(rep.predict(X), engine.predict(X))
    # deadline/trace_ctx are accepted and inert in-process
    out = rep.predict(X, deadline=time.perf_counter() + 10,
                      trace_ctx=inject_context("req-1"))
    assert np.array_equal(out, engine.predict(X))


def test_in_process_transport_dispatch_matches_engine_bitwise():
    engine = make_engine()
    t = InProcessTransport(engine)
    X = rows(5)
    assert np.array_equal(t.dispatch(X), engine.predict(X))
    # the timing slot behaves exactly as a direct call: dispatch with
    # record_timings=True leaves the split for the single consumer
    t.dispatch(X, record_timings=True)
    timing = engine.pop_timings()
    assert timing is not None and timing["version"] == 0


def test_router_over_explicit_in_process_transports_unchanged():
    engine = make_engine()
    reps = [Replica(i, engine,
                    transport=InProcessTransport(engine))
            for i in range(2)]
    router = FailoverRouter(reps, policy="round_robin")
    X = rows(4)
    assert np.array_equal(router.predict(X), engine.predict(X))
    assert engine.compile_count == len(engine.buckets)


# -- socket round trip -------------------------------------------------

def test_socket_dispatch_parity_with_direct_call():
    engine = make_engine()
    with PodWorker(engine) as w:
        with SocketTransport(("127.0.0.1", w.port)) as t:
            for n in (1, 3, 8):
                X = rows(n, seed=n)
                assert np.allclose(t.dispatch(X), engine.predict(X),
                                   atol=0)
            assert t.dispatches == 3
    assert w.dispatches == 3 and w.frame_errors == 0


def test_socket_dispatch_version_pin_rides_the_wire():
    engine = make_engine()
    engine.install_weights(7, {"w": rows(C, seed=3)})
    pod = _facade_for(engine)
    with PodWorker(engine) as w:
        pod.endpoints = [("127.0.0.1", w.port)]
        with SocketTransport(("127.0.0.1", w.port), client=pod) as t:
            t.dispatch(rows(2), version=7)
            timing = pod.pop_timings()
    assert timing["version"] == 7
    # single-consumer slot: popped means gone
    assert pod.pop_timings() is None


def _facade_for(engine):
    """A PodClientEngine built without a handshake (unit tests that
    only need the timing slot / metadata surface)."""
    pod = PodClientEngine.__new__(PodClientEngine)
    pod.endpoints = []
    pod.connect_timeout_s = 5.0
    pod.max_frame_bytes = 1 << 26
    pod._timings = None
    pod.buckets = tuple(engine.buckets)
    pod.input_dim = engine.input_dim
    pod.num_classes = engine.num_classes
    pod._version = int(getattr(engine, "version", 0))
    pod._vlock = threading.Lock()
    pod._swap_lock = threading.Lock()
    return pod


def test_worker_rejects_garbage_and_keeps_serving():
    engine = StubEngine()
    with PodWorker(engine) as w:
        # a garbage connection gets a loud typed error frame back and
        # is dropped...
        with socket.create_connection(("127.0.0.1", w.port),
                                      timeout=5) as s:
            s.settimeout(5.0)
            s.sendall(b"NOT A FRAME AT ALL PADPADPAD")
            resp, _ = read_frame(s)
            assert resp["kind"] == "error"
            assert resp["transient"] is False
            assert "magic" in resp["error"]
        # ...and the worker keeps serving real clients afterwards
        with SocketTransport(("127.0.0.1", w.port)) as t:
            out = t.dispatch(rows(2))
            assert out.shape == (2, C)
    assert w.frame_errors == 1


def test_transport_refused_and_reconnect_backoff():
    # nothing listening: connect refused, typed transient; the second
    # failure lands inside the backoff window and fast-fails without
    # touching the wire
    with socket.socket() as probe:
        probe.bind(("127.0.0.1", 0))
        dead_port = probe.getsockname()[1]
    t = SocketTransport(("127.0.0.1", dead_port), backoff_ms=200.0)
    with pytest.raises(TransportRefused, match="connect"):
        t.dispatch(rows(1))
    t0 = time.perf_counter()
    with pytest.raises(TransportRefused, match="backoff"):
        t.dispatch(rows(1))
    assert time.perf_counter() - t0 < 0.1  # fast-fail, no connect wait
    assert t.stats()["connect_failures"] == 1


# -- deadline budget across the hop -----------------------------------

class _HeaderSpy(PodWorker):
    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.headers = []

    def _handle_dispatch(self, header, payload):
        self.headers.append(dict(header))
        return super()._handle_dispatch(header, payload)


def test_budget_shrinks_across_the_hop():
    engine = StubEngine()
    with _HeaderSpy(engine) as w:
        with SocketTransport(("127.0.0.1", w.port)) as t:
            deadline = time.perf_counter() + 0.8
            time.sleep(0.25)  # burn budget BEFORE dispatching
            t.dispatch(rows(2), deadline=deadline)
    (hdr,) = w.headers
    # the frame carried what REMAINED, not the original allowance
    assert 0.0 < hdr["budget_s"] <= 0.56
    # and a deadline-free dispatch carries none
    with _HeaderSpy(engine) as w2:
        with SocketTransport(("127.0.0.1", w2.port)) as t2:
            t2.dispatch(rows(2))
    assert w2.headers[0]["budget_s"] is None


def test_exhausted_budget_fails_before_any_io():
    engine = StubEngine()
    with PodWorker(engine) as w:
        with SocketTransport(("127.0.0.1", w.port)) as t:
            with pytest.raises(TransportTimeout, match="exhausted"):
                t.dispatch(rows(1),
                           deadline=time.perf_counter() - 0.01)
    assert w.dispatches == 0  # nothing crossed the wire


def test_read_timeout_derived_from_deadline():
    # a wedged worker (slow predict) against a tight budget: the read
    # times out at ~the budget, not at the 10s default io timeout
    engine = StubEngine(sleep_s=1.5)
    with PodWorker(engine) as w:
        with SocketTransport(("127.0.0.1", w.port)) as t:
            t0 = time.perf_counter()
            with pytest.raises(TransportTimeout):
                t.dispatch(rows(1),
                           deadline=time.perf_counter() + 0.3)
            assert time.perf_counter() - t0 < 1.0


def test_worker_refuses_expired_budget():
    # the worker-side half of the deadline contract: a frame whose
    # budget reads exhausted is refused transiently, never dispatched
    engine = StubEngine()
    with PodWorker(engine) as w:
        with socket.create_connection(("127.0.0.1", w.port),
                                      timeout=5) as s:
            s.settimeout(5.0)
            hdr, payload = pack_batch(rows(1))
            hdr.update(kind="dispatch", version=None, budget_s=-0.1)
            write_frame(s, hdr, payload)
            resp, _ = read_frame(s)
    assert resp["kind"] == "error" and resp["transient"] is True
    assert "budget" in resp["error"]
    assert w.dispatches == 0


# -- TRACECTX propagation ---------------------------------------------

def test_tracectx_propagates_end_to_end():
    engine = StubEngine()
    worker_tracer = Tracer()
    with PodWorker(engine, tracer=worker_tracer) as w:
        with SocketTransport(("127.0.0.1", w.port)) as t:
            t.dispatch(rows(3),
                       trace_ctx=inject_context("req-77", "s-5"))
            t.dispatch(rows(1))  # no context: no orphan span either
    spans = [r for r in worker_tracer.records()
             if r["name"] == "pod_dispatch"]
    assert len(spans) == 1
    (sp,) = spans
    assert sp["trace_id"] == "req-77"
    assert sp["parent_id"] == "s-5"
    assert sp["attrs"]["rows"] == 3
    assert sp["attrs"]["model_version"] == 0


def test_malformed_tracectx_is_loud_not_silent():
    # a dropped/garbled carrier must surface as a loud error, not a
    # silently-orphaned span tree (the extract_context contract,
    # enforced across the wire)
    engine = StubEngine()
    worker_tracer = Tracer()
    with PodWorker(engine, tracer=worker_tracer) as w:
        with SocketTransport(("127.0.0.1", w.port)) as t:
            with pytest.raises(RuntimeError, match="trace-context"):
                t.dispatch(rows(1), trace_ctx="TRACECTX.v9;;;;")


def test_service_injects_batch_context_over_the_pod(tmp_path):
    """End to end through the full stack: ServingService detects the
    router's trace_ctx capability, sends the batch id as the carrier,
    and the worker's spans join exactly those traces — request spans
    still landing exactly once, router-side."""
    engines = [StubEngine(seed=1), StubEngine(seed=1)]
    workers = [PodWorker(e, worker_id=i).start()
               for i, e in enumerate(engines)]
    worker_tracers = [Tracer(), Tracer()]
    for w, tr in zip(workers, worker_tracers):
        w.tracer = tr
    try:
        eps = [("127.0.0.1", w.port) for w in workers]
        pod = PodClientEngine(eps)
        reps = [Replica(i, pod, transport=SocketTransport(
            eps[i], client=pod, host_index=i))
            for i in range(2)]
        tracer = Tracer()
        with FailoverRouter(reps, policy="round_robin") as router:
            with ServingService(router, tracer=tracer) as svc:
                futs = [svc.submit(rows(2, seed=i), timeout_s=30.0)
                        for i in range(10)]
                for f in futs:
                    f.result(timeout=30)
        req_spans = [r for r in tracer.records()
                     if r["name"] == "request"]
        ids = [r["trace_id"] for r in req_spans]
        assert sorted(ids) == sorted(f.request_id for f in futs)
        batch_ids = {r["attrs"]["batch"] for r in req_spans}
        pod_spans = [r for tr in worker_tracers for r in tr.records()
                     if r["name"] == "pod_dispatch"]
        assert pod_spans
        assert {r["trace_id"] for r in pod_spans} <= batch_ids
    finally:
        for w in workers:
            w.stop()


# -- NetChaosSpec / NetChaosPlan --------------------------------------

def test_net_chaos_spec_parse_full_grammar():
    s = NetChaosSpec.parse(
        "partition=0.02:250,refuse=0.05,lag=0.1:20,kill_host=1@12,"
        "kill_host=0@3,seed=7")
    assert (s.partition, s.partition_s) == (0.02, 0.25)
    assert (s.refuse, s.lag, s.lag_s, s.seed) == (0.05, 0.1, 0.02, 7)
    assert dict(s.kill_host) == {1: 12, 0: 3}
    # bare rates keep the shape defaults; empty spec is clean
    s2 = NetChaosSpec.parse("partition=0.1,lag=0.2")
    assert s2.partition_s == 0.25 and s2.lag_s == 0.02
    assert NetChaosSpec.parse("") == NetChaosSpec()


@pytest.mark.parametrize("bad, match", [
    ("boom=1", "unknown net chaos spec key"),
    ("partition", "not key=value"),
    ("partition=lots", "partition=lots"),
    ("refuse=1.5", r"must be in \[0, 1\]"),
    ("partition=0.6,refuse=0.6", "sum to <= 1"),
    ("kill_host=3", "HOST@DISPATCH"),
    ("kill_host=1@2,kill_host=1@5", "dies once"),
])
def test_net_chaos_spec_parse_rejects(bad, match):
    with pytest.raises(ValueError, match=match):
        NetChaosSpec.parse(bad)


def test_net_chaos_same_seed_bitwise_same_schedule():
    spec = NetChaosSpec.parse(
        "partition=0.05:100,refuse=0.1,lag=0.2:10,kill_host=2@8,"
        "seed=23")
    p1 = NetChaosPlan.build(spec, 4, horizon=512)
    p2 = NetChaosPlan.build(spec, 4, horizon=512)
    assert np.array_equal(p1.roles, p2.roles)
    assert p1.kills == p2.kills == {2: 8}
    assert p1.counts() == p2.counts()
    # a different seed is a different schedule
    p3 = NetChaosPlan.build(
        NetChaosSpec.parse("partition=0.05:100,refuse=0.1,lag=0.2:10,"
                           "seed=24"), 4, horizon=512)
    assert not np.array_equal(p1.roles, p3.roles)
    # roles are mutually exclusive per cell, rates roughly honored
    total = p1.roles.size
    assert 0 < p1.counts()["partition"] < 0.15 * total
    assert 0 < p1.counts()["refuse"] < 0.2 * total


def test_net_chaos_scripted_and_role_lookup():
    plan = NetChaosPlan.scripted(3, partitions={0: [2, 5]},
                                 refuses={1: [0]}, lags={2: [1]},
                                 kills={1: 4}, horizon=16)
    assert plan.role(0, 2) == NET_PARTITION
    assert plan.role(1, 0) == NET_REFUSE
    assert plan.role(2, 1) == NET_LAG
    assert plan.role(0, 3) == NET_CLEAN
    assert plan.role(0, 99) == NET_CLEAN  # past horizon: clean
    assert plan.kill_at(1) == 4 and plan.kill_at(0) is None
    with pytest.raises(ValueError, match="two roles"):
        NetChaosPlan.scripted(2, partitions={0: [1]},
                              refuses={0: [1]})
    with pytest.raises(ValueError, match="out of range"):
        NetChaosPlan.scripted(2, kills={5: 1})


def test_resolve_net_chaos_surface():
    assert resolve_net_chaos(None, 3) is None
    p = resolve_net_chaos("refuse=0.5,seed=1", 3)
    assert isinstance(p, NetChaosPlan) and p.n_hosts == 3
    assert resolve_net_chaos(p, 2) is p  # covers 2 hosts: fine
    with pytest.raises(ValueError, match="rebuild"):
        resolve_net_chaos(NetChaosPlan.build(NetChaosSpec(), 1), 3)
    with pytest.raises(TypeError):
        resolve_net_chaos(42, 3)


def test_chaos_injection_at_the_transport():
    engine = StubEngine()
    with PodWorker(engine) as w:
        plan = NetChaosPlan.scripted(
            1, refuses={0: [0]}, partitions={0: [1]}, lags={0: [2]},
            horizon=64, partition_s=0.15, lag_s=0.05)
        with SocketTransport(("127.0.0.1", w.port), chaos=plan,
                             host_index=0, n_hosts=1) as t:
            with pytest.raises(TransportRefused, match="net-chaos"):
                t.dispatch(rows(1))
            t0 = time.perf_counter()
            with pytest.raises(TransportTimeout, match="partition"):
                t.dispatch(rows(1))
            stall = time.perf_counter() - t0
            assert 0.1 <= stall < 1.0
            t0 = time.perf_counter()
            out = t.dispatch(rows(2))  # dispatch 2: lag, then serves
            assert time.perf_counter() - t0 >= 0.05
            assert out.shape == (2, C)
            assert t.faults_injected == {"partition": 1, "refuse": 1,
                                         "lag": 1, "kill": 0}


def test_partition_stall_bounded_by_budget():
    plan = NetChaosPlan.scripted(1, partitions={0: [0]}, horizon=8,
                                 partition_s=5.0)
    with SocketTransport(("127.0.0.1", 1), chaos=plan, host_index=0,
                         n_hosts=1) as t:
        t0 = time.perf_counter()
        with pytest.raises(TransportTimeout, match="partition"):
            t.dispatch(rows(1),
                       deadline=time.perf_counter() + 0.1)
        assert time.perf_counter() - t0 < 1.0


# -- SIGKILL mid-batch -------------------------------------------------

def _slow_worker_proc(port_file: str) -> None:
    """Forked child: a pod worker whose predict stalls long enough
    for the parent to SIGKILL it mid-dispatch."""
    engine = StubEngine(sleep_s=5.0)
    worker = PodWorker(engine)
    with open(port_file + ".tmp", "w") as f:
        f.write(f"{worker.port}\n")
    os.replace(port_file + ".tmp", port_file)
    worker.start()
    time.sleep(60)


@pytest.mark.skipif(not hasattr(os, "fork"), reason="needs fork")
def test_sigkill_mid_batch_requeues_within_deadline(tmp_path):
    """THE pod failure mode: the worker process dies BY SIGKILL while
    a batch is in flight on its socket. The transport fails
    transiently (reset/EOF), the router's circuit counts it and the
    in-flight batch requeues against the surviving replica — within
    the original request deadline, nothing lost, zero recompiles."""
    port_file = str(tmp_path / "port")
    ctx = multiprocessing.get_context("fork")
    proc = ctx.Process(target=_slow_worker_proc, args=(port_file,),
                       daemon=True)
    proc.start()
    deadline = time.perf_counter() + 30
    while not os.path.exists(port_file):
        assert time.perf_counter() < deadline, "worker never came up"
        time.sleep(0.02)
    with open(port_file) as f:
        port = int(f.read().strip())
    engine = StubEngine(seed=1)  # the survivor's (identical) weights
    victim = Replica(0, engine, transport=SocketTransport(
        ("127.0.0.1", port), io_timeout_s=20.0))
    survivor = Replica(1, engine)  # in-process: always healthy
    router = FailoverRouter([victim, survivor], policy="round_robin")
    X = rows(4)

    def kill_soon():
        time.sleep(0.3)  # let the dispatch get in flight first
        os.kill(proc.pid, signal.SIGKILL)

    killer = threading.Thread(target=kill_soon, daemon=True)
    killer.start()
    t0 = time.perf_counter()
    out = router.predict(X, deadline=time.perf_counter() + 10.0)
    took = time.perf_counter() - t0
    killer.join()
    proc.join(timeout=10)
    # the batch was answered by the survivor, within the deadline
    assert np.array_equal(out, engine.predict(X))
    assert took < 10.0
    stats = router.replica_stats()
    assert stats["requeues"] >= 1
    assert stats["replicas"]["0"]["failed"] >= 1
    assert stats["replicas"]["1"]["ok"] == 1
    # and the victim's NEXT dispatch fails fast (refused/reset), so
    # the circuit keeps counting toward open — no hang, no zombie
    with pytest.raises((TransportError, FrameError)):
        victim.transport.dispatch(rows(1))


# -- worker version agreement -----------------------------------------

def test_swap_announce_lands_every_worker_on_one_version():
    engines = [StubEngine(seed=1), StubEngine(seed=1)]
    workers = [PodWorker(e, worker_id=i).start()
               for i, e in enumerate(engines)]
    try:
        eps = [("127.0.0.1", w.port) for w in workers]
        pod = PodClientEngine(eps)
        new_w = rows(C, seed=42)
        v = pod.swap_weights({"w": new_w})
        assert v == 1
        assert pod.version == 1
        assert [e.version for e in engines] == [1, 1]
        assert all(np.array_equal(e.W, new_w) for e in engines)
        assert pod.last_announce["acks"] == 2
        # post-swap dispatches report the agreed version off the wire
        with SocketTransport(eps[0], client=pod) as t:
            t.dispatch(rows(1))
            assert pod.pop_timings()["version"] == 1
    finally:
        for w in workers:
            w.stop()


def test_swap_announce_with_dead_worker_acks_survivors():
    engine = StubEngine(seed=1)
    with PodWorker(engine) as w:
        with socket.socket() as probe:
            probe.bind(("127.0.0.1", 0))
            dead_port = probe.getsockname()[1]
        pod = PodClientEngine([("127.0.0.1", w.port),
                               ("127.0.0.1", dead_port)])
        v = pod.swap_weights({"w": rows(C, seed=9)})
        assert v == 1 and pod.last_announce["acks"] == 1
        assert len(pod.last_announce["failures"]) == 1
        assert engine.version == 1
        # stats surface the death the announce skipped
        stats = pod.worker_stats()
        assert [bool(m.get("dead")) for m in stats] == [False, True]
    # every endpoint dead: the announce must FAIL, and the client's
    # notion of live must not advance
    pod2 = PodClientEngine.__new__(PodClientEngine)
    pod2.endpoints = [("127.0.0.1", dead_port)]
    pod2.connect_timeout_s = 1.0
    pod2.max_frame_bytes = 1 << 20
    pod2._timings = None
    pod2._version = 1
    pod2._vlock = threading.Lock()
    pod2._swap_lock = threading.Lock()
    with pytest.raises(TransportError, match="no worker"):
        pod2.swap_weights({"w": rows(C)})
    assert pod2.version == 1


def test_real_engine_pod_swap_and_service_end_to_end():
    """The full stack over real engines: two workers each hosting
    their OWN ServingEngine (separate processes in production — the
    unit here shares a process but nothing else), a facade handshake,
    a mid-stream broadcast swap, and the post-swap version pin on
    spans — with zero recompiles on either worker engine."""
    rng = np.random.RandomState(1)
    weights = {"w": rng.randn(C, D).astype(np.float32)}
    engines = []
    for _ in range(2):
        e = ServingEngine({k: v.copy() for k, v in weights.items()},
                          buckets=(1, 8))
        e.warmup()
        engines.append(e)
    cc0 = [e.compile_count for e in engines]
    workers = [PodWorker(e, worker_id=i).start()
               for i, e in enumerate(engines)]
    try:
        eps = [("127.0.0.1", w.port) for w in workers]
        pod = PodClientEngine(eps)
        assert pod.buckets == (1, 8) and pod.input_dim == D
        reps = [Replica(i, pod, transport=SocketTransport(
            eps[i], client=pod, host_index=i))
            for i in range(2)]
        tracer = Tracer()
        with FailoverRouter(reps, policy="round_robin") as router:
            with ServingService(router, tracer=tracer) as svc:
                pre = [svc.submit(rows(2, seed=i), timeout_s=30.0)
                       for i in range(6)]
                for f in pre:
                    f.result(timeout=30)
                v = router.swap_weights(
                    {"w": rng.randn(C, D).astype(np.float32)})
                post = [svc.submit(rows(2, seed=i), timeout_s=30.0)
                        for i in range(6)]
                for f in post:
                    f.result(timeout=30)
        assert v == 1
        assert [e.version for e in engines] == [1, 1]
        post_ids = {f.request_id for f in post}
        req_spans = [r for r in tracer.records()
                     if r["name"] == "request"]
        vers = {r["attrs"]["model_version"] for r in req_spans
                if r["trace_id"] in post_ids}
        assert vers == {1}
        # the zero-recompile pin crosses the process seam: weights
        # stay call arguments on every worker
        assert [e.compile_count for e in engines] == cc0
    finally:
        for w in workers:
            w.stop()


def test_socket_dispatch_single_row_duality():
    # the engine.predict row/batch duality crosses the wire: a (d,)
    # row dispatches as (1, d) and comes back as a (C,) row
    engine = make_engine()
    with PodWorker(engine) as w:
        with SocketTransport(("127.0.0.1", w.port)) as t:
            x = rows(1)[0]
            out = t.dispatch(x)
            assert out.shape == (C,)
            assert np.allclose(out, engine.predict(x), atol=0)


def test_concurrent_swaps_serialize_one_agreed_version():
    """Review pin (the one-agreed-version invariant under
    concurrency): two racing swap_weights announces must SERIALIZE —
    distinct version numbers, every worker converging on the same
    final weights under the same final number — never two different
    weight sets wearing one version."""
    engines = [StubEngine(seed=1), StubEngine(seed=1)]
    workers = [PodWorker(e, worker_id=i).start()
               for i, e in enumerate(engines)]
    try:
        eps = [("127.0.0.1", w.port) for w in workers]
        pod = PodClientEngine(eps)
        wa, wb = rows(C, seed=100), rows(C, seed=200)
        got = []
        barrier = threading.Barrier(2)

        def swap(wts):
            barrier.wait()
            got.append(pod.swap_weights({"w": wts}))

        ts = [threading.Thread(target=swap, args=(w,))
              for w in (wa, wb)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        # distinct numbers — nobody raced into the other's slot
        assert sorted(got) == [1, 2]
        assert pod.version == 2
        # and the POD agrees with itself: same version, same weights
        # on every worker (last announce wins everywhere)
        assert [e.version for e in engines] == [2, 2]
        assert np.array_equal(engines[0].W, engines[1].W)
    finally:
        for w in workers:
            w.stop()


def test_one_d_engine_output_keeps_rank_across_the_wire():
    """Review pin (transport shape equivalence): a hosted engine
    answering 1-D predictions must come back 1-D — the wire's
    (rows, cols) framing cannot silently promote it to a column."""

    class OneD(StubEngine):
        def predict(self, X, version=None, record_timings=True):
            return super().predict(X).argmax(-1).astype(np.float32)

    engine = OneD()
    with PodWorker(engine) as w:
        with SocketTransport(("127.0.0.1", w.port)) as t:
            direct = InProcessTransport(engine)
            X = rows(5)
            assert t.dispatch(X).shape == direct.dispatch(X).shape \
                == (5,)


def test_reconnects_counts_only_reconnects():
    # the first lazy connect is not recovery evidence; a drop and a
    # fresh connect afterwards is
    engine = StubEngine()
    with PodWorker(engine) as w:
        with SocketTransport(("127.0.0.1", w.port)) as t:
            t.dispatch(rows(1))
            assert t.reconnects == 0
            t.close()  # drop the connection
            t.dispatch(rows(1))
            assert t.reconnects == 1


def test_lag_stall_spends_the_deadline_budget():
    """Review pin: a lag cell that outlives the remaining budget must
    end in TransportTimeout BEFORE any I/O — a stale pre-stall budget
    read would ship a positive-looking budget_s header for a caller
    who already gave up."""
    engine = StubEngine()
    plan = NetChaosPlan.scripted(1, lags={0: [0]}, horizon=8,
                                 lag_s=0.15)
    with PodWorker(engine) as w:
        with SocketTransport(("127.0.0.1", w.port), chaos=plan,
                             host_index=0, n_hosts=1) as t:
            with pytest.raises(TransportTimeout, match="exhausted"):
                t.dispatch(rows(1),
                           deadline=time.perf_counter() + 0.05)
    assert w.dispatches == 0  # nothing crossed the wire


def test_net_chaos_plan_rejects_negative_kill_index():
    with pytest.raises(ValueError, match="must be >= 0"):
        NetChaosPlan.scripted(2, kills={0: -3})


# -- rejoin resync: the announce-gap fix (ISSUE 16) --------------------

def test_rejoining_worker_resyncs_to_agreed_version():
    """A swap announced while a worker is down used to leave the
    rejoiner serving stale weights under the pod's name (the announce
    gap). The ``sync`` handshake closes it: a worker started with
    ``peers=`` re-requests the agreed version before serving."""
    survivor = make_engine()
    with PodWorker(survivor, worker_id=0) as wa:
        pod = PodClientEngine([("127.0.0.1", wa.port)])
        new_w = rows(C, seed=42)
        assert pod.swap_weights({"w": new_w}) == 1
        # the rejoiner: fresh engine still on version 0 weights
        rejoiner = make_engine()
        with PodWorker(rejoiner, worker_id=1,
                       peers=[("127.0.0.1", wa.port)]) as wb:
            assert rejoiner.version == 1
            assert np.array_equal(
                np.asarray(rejoiner.params["w"]), new_w)
            assert wb.resyncs == 1
            # the handshake surfaces in the meta frame
            meta, _ = pod.control(("127.0.0.1", wb.port),
                                  {"kind": "hello"})
            assert meta["resyncs"] == 1 and meta["version"] == 1
            # and the rejoiner serves the synced weights on the wire
            with SocketTransport(("127.0.0.1", wb.port),
                                 client=pod) as t:
                X = rows(2)
                np.testing.assert_allclose(
                    t.dispatch(X), X @ new_w.T, rtol=1e-5)


def test_resync_picks_newest_version_not_first_peer():
    old, new = make_engine(), make_engine()
    old.swap_weights({"w": rows(C, seed=7)}, version=1)
    new.swap_weights({"w": rows(C, seed=9)}, version=3)
    with PodWorker(old) as wo, PodWorker(new) as wn:
        rejoiner = make_engine()
        w = PodWorker(rejoiner, peers=[("127.0.0.1", wo.port),
                                       ("127.0.0.1", wn.port)])
        with w:
            assert rejoiner.version == 3
            assert np.array_equal(np.asarray(rejoiner.params["w"]),
                                  rows(C, seed=9))


def test_resync_skips_weightless_and_dead_peers():
    # a peer whose engine exports no params answers meta (skipped);
    # a dead endpoint is skipped; a lone survivor must still come up
    with PodWorker(StubEngine(seed=1)) as stub_w:
        with socket.socket() as probe:
            probe.bind(("127.0.0.1", 0))
            dead = probe.getsockname()[1]
        eng = make_engine()
        w = PodWorker(eng, peers=[("127.0.0.1", dead),
                                  ("127.0.0.1", stub_w.port)])
        with w:
            assert w.resync() is None  # nothing newer anywhere
            assert eng.version == 0 and w.resyncs == 0
            # and it serves regardless: rejoin must not deadlock on
            # an unsyncable pod
            pod = PodClientEngine([("127.0.0.1", w.port)])
            with SocketTransport(("127.0.0.1", w.port),
                                 client=pod) as t:
                assert t.dispatch(rows(2)).shape == (2, C)


def test_resync_ignores_older_peer_versions():
    # joining the OLDER side of a mid-announce pod would re-open the
    # gap one announce later; a peer behind this worker is ignored
    behind = make_engine()  # version 0
    with PodWorker(behind) as wb:
        eng = make_engine()
        eng.swap_weights({"w": rows(C, seed=11)}, version=5)
        w = PodWorker(eng, peers=[("127.0.0.1", wb.port)])
        with w:
            assert eng.version == 5 and w.resyncs == 0


def test_sync_frame_serves_live_weights_over_the_wire():
    eng = make_engine()
    eng.swap_weights({"w": rows(C, seed=13)}, version=2)
    with PodWorker(eng) as w:
        with socket.create_connection(("127.0.0.1", w.port),
                                      timeout=5.0) as sock:
            sock.settimeout(5.0)
            write_frame(sock, {"kind": "sync"})
            resp, payload = read_frame(sock, 1 << 30)
        assert resp["kind"] == "weights" and resp["version"] == 2
        params, rff = unpack_weights(payload)
        assert np.array_equal(np.asarray(params["w"]),
                              rows(C, seed=13))


# -- byzantine-hardened pod sync (ISSUE 18) ----------------------------

def test_sync_timeout_is_typed_and_bounds_the_handshake():
    """A peer that ACCEPTS the connection but never answers (the
    wedged process) must cost at most the handshake budget: the
    per-peer exchange raises typed SyncTimeout, resync counts it and
    moves on, and the rejoiner comes up in bounded time."""
    wedge = socket.socket()
    wedge.bind(("127.0.0.1", 0))
    wedge.listen(1)  # kernel accepts; nobody ever answers
    try:
        ep = ("127.0.0.1", wedge.getsockname()[1])
        eng = make_engine()
        w = PodWorker(eng, peers=[ep])
        with pytest.raises(SyncTimeout, match="sync peer"):
            w._sync_one(ep, 0.2)
        t0 = time.perf_counter()
        assert w.resync(timeout_s=0.4) is None
        assert time.perf_counter() - t0 < 2.0
        assert w.sync_timeouts >= 1
        assert isinstance(SyncTimeout("x"), TransportTimeout)
    finally:
        wedge.close()


def test_stale_epoch_announce_refused_loudly():
    """The epoch fence: an announce whose epoch is at or below the
    last accepted one is a replay/stale broadcast — refused with a
    permanent error frame, counted, and the installed weights are
    untouched. Frames WITHOUT an epoch (legacy clients) install as
    before."""
    eng = make_engine()
    with PodWorker(eng) as w:
        pod = PodClientEngine([("127.0.0.1", w.port)])
        fresh = rows(C, seed=21)
        blob = pack_weights({"w": fresh}, None)
        resp, _ = pod.control(
            ("127.0.0.1", w.port),
            {"kind": "swap", "version": 1, "epoch": 2}, blob)
        assert resp["kind"] == "ok" and eng.version == 1
        # replayed epoch (== last accepted): refused loudly
        stale = pack_weights({"w": rows(C, seed=22)}, None)
        resp, _ = pod.control(
            ("127.0.0.1", w.port),
            {"kind": "swap", "version": 5, "epoch": 2}, stale)
        assert resp["kind"] == "error"
        assert resp["transient"] is False
        assert "stale announce epoch" in resp["error"]
        assert eng.version == 1
        assert np.array_equal(np.asarray(eng.params["w"]), fresh)
        assert w.stale_refused == 1
        # a legacy epoch-free frame still installs (byte-compat)
        resp, _ = pod.control(
            ("127.0.0.1", w.port),
            {"kind": "swap", "version": 2}, stale)
        assert resp["kind"] == "ok" and eng.version == 2


def test_forged_fingerprint_announce_rejected():
    """An announce whose payload does not hash to its claimed
    fingerprint never installs — permanent error, counted."""
    eng = make_engine()
    with PodWorker(eng) as w:
        pod = PodClientEngine([("127.0.0.1", w.port)])
        before = np.asarray(eng.params["w"]).copy()
        blob = pack_weights({"w": rows(C, seed=23)}, None)
        resp, _ = pod.control(
            ("127.0.0.1", w.port),
            {"kind": "swap", "version": 7, "epoch": 9,
             "fingerprint": "0" * 64}, blob)
        assert resp["kind"] == "error"
        assert resp["transient"] is False
        assert "fingerprint mismatch" in resp["error"]
        assert eng.version == 0
        assert np.array_equal(np.asarray(eng.params["w"]), before)
        assert w.forge_rejected == 1


def test_announce_restart_race_heals_via_straggler_repass():
    """The scripted announce-vs-restart race (the shrunk regression's
    mechanism, deterministic): worker A is dead when the announce
    reaches it first, restarts mid-announce (rejoining off a peer the
    announce has NOT reached yet — so resync finds nothing newer), and
    would be left on the old version forever. The client's straggler
    re-pass retries failed endpoints once after the first pass and
    lands A on the announced version — both workers agree."""
    eng_a, eng_b = make_engine(), make_engine()
    wa = PodWorker(eng_a, worker_id=0).start()
    port_a = wa.port
    with PodWorker(eng_b, worker_id=1) as wb:
        eps = [("127.0.0.1", port_a), ("127.0.0.1", wb.port)]
        pod = PodClientEngine(eps)
        wa.stop()  # dead at announce time
        restarted = []

        def rejoin(ep, ok):
            if ep == eps[0] and not ok and not restarted:
                # restart on the SAME port, syncing from B — which
                # has not seen the announce yet (endpoint order)
                w2 = PodWorker(eng_a, worker_id=0, port=port_a,
                               peers=[eps[1]]).start()
                restarted.append(w2)

        pod.on_announce = rejoin
        try:
            new_w = rows(C, seed=31)
            assert pod.swap_weights({"w": new_w}) == 1
            assert restarted, "the race script never fired"
            assert pod.last_announce["acks"] == 2
            assert pod.last_announce["failures"] == []
            assert eng_a.version == eng_b.version == 1
            assert np.array_equal(np.asarray(eng_a.params["w"]), new_w)
        finally:
            pod.on_announce = None
            for w2 in restarted:
                w2.stop()


def test_resync_quorum_rejects_self_consistent_forger():
    """The byzantine sync peer: serves forged weights under a claimed
    newer version WITH a self-consistent fingerprint (content
    verification alone cannot unmask it). The rejoiner's strict
    -majority fingerprint quorum rejects the disagreeing reply and
    installs the honest pod's version instead."""
    honest_w = rows(C, seed=41)
    honest = []
    for i in range(3):
        e = make_engine()
        e.swap_weights({"w": honest_w}, version=1)
        honest.append(PodWorker(e, worker_id=i).start())
    liar_eng = make_engine()
    liar_eng.swap_weights({"w": honest_w}, version=1)
    liar = PodWorker(liar_eng, worker_id=3, forge_sync=99).start()
    try:
        # the forgery IS self-consistent: its reply fingerprint hashes
        # its own (garbage) payload under the claimed version
        with socket.create_connection(("127.0.0.1", liar.port),
                                      timeout=5.0) as sock:
            sock.settimeout(5.0)
            write_frame(sock, {"kind": "sync"})
            resp, payload = read_frame(sock, 1 << 30)
        assert resp["version"] == 99
        params, rff = unpack_weights(payload)
        assert resp["fingerprint"] == weights_fingerprint(
            params, rff, 99)
        assert not np.array_equal(np.asarray(params["w"]), honest_w)
        # the rejoiner: quorum of 3 honest vs 1 forged
        peers = [("127.0.0.1", w.port) for w in honest] + [
            ("127.0.0.1", liar.port)]
        rejoiner = make_engine()
        with PodWorker(rejoiner, worker_id=4, peers=peers) as w:
            assert rejoiner.version == 1
            assert np.array_equal(np.asarray(rejoiner.params["w"]),
                                  honest_w)
            assert w.forge_rejected == 1
            assert w.resyncs == 1
    finally:
        for w in honest:
            w.stop()
        liar.stop()


def test_resync_rejects_reply_disowning_its_payload():
    """A reply whose fingerprint does not hash its own payload (wire
    corruption, or a forger too lazy to re-hash) is dropped before the
    quorum even runs."""

    class _Corrupt(PodWorker):
        def _handle_sync(self):
            resp, blob = super()._handle_sync()
            resp = dict(resp, version=9,
                        fingerprint="f" * 64)  # disowns the payload
            return resp, blob

    eng = make_engine()
    eng.swap_weights({"w": rows(C, seed=43)}, version=1)
    with _Corrupt(eng) as bad:
        rejoiner = make_engine()
        w = PodWorker(rejoiner, peers=[("127.0.0.1", bad.port)])
        with w:
            assert rejoiner.version == 0  # nothing trusted to install
            assert w.forge_rejected == 1
