# Repo tooling namespace (check_bench_schema, export_artifacts,
# graftlint). Kept a package so `python -m tools.graftlint` works from
# the repo root; the standalone `python tools/<script>.py` spellings are
# unchanged.
