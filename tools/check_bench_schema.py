#!/usr/bin/env python
"""Validate committed bench artifacts against the driver contract.

Run as a tier-1 test (``tests/test_check_bench_schema.py``) and
standalone (``python tools/check_bench_schema.py [--root DIR]``), so a
malformed ``BENCH_*.json`` / ``BENCH_SERVE_*.json`` / ``MULTICHIP_*.json``
can never land silently — the round driver parses these files, and a
key drift would only surface as a null harvest rows later.

Three artifact families, three rule sets:

- ``BENCH_rNN.json`` — the DRIVER-side wrapper around a ``bench.py``
  run: ``{n, cmd, rc, tail, parsed}``. On success (``rc == 0``)
  ``parsed`` must be the headline record (metric/value/unit present;
  value > 0) and the LAST JSON line in ``tail`` must carry the same
  metric — the headline-metric-LAST contract the driver parses by. On
  failure ``parsed`` may be null (the honest shape of an aborted
  capture, e.g. the r02 tunnel outage). The ``platform`` label is
  required from capture 2 on (r01 predates the label; grandfathered
  explicitly rather than loosening the rule for new artifacts).
- ``BENCH_SERVE_rNN.json`` — ``serve_bench.py``'s own artifact:
  ``schema`` in the ``BENCH_SERVE.`` family, a top-level ``platform``
  label, a non-empty per-bucket latency table, a mixed-stream section
  with a positive request count, and the ``recompiles_after_warmup``
  field the zero-recompile pin reads. From schema v2 on, the
  ``rollout`` section (the ISSUE 6 continuous-deployment leg) is also
  required: swap count and latency, in-flight p95 across swaps, the
  canary/rollback-drill verdicts, and zero recompiles during swaps —
  v1 artifacts (r01) predate the leg and are grandfathered by schema
  version, so the rule stays strict for every artifact that could
  carry it. From schema v3 on, the ``chaos`` section (the ISSUE 7
  replica-fleet failover leg) is required too: replica/kill/requeue/
  hedge-win counts, p95 with AND without chaos, zero lost requests,
  and zero recompiles during chaos — the abort-grade pins the bench
  enforces, re-checked here so a hand-edited artifact can never land
  green. From schema v4 on, the ``cold_start`` section (the ISSUE 9
  AOT-artifact leg) is required as well: both replica start modes
  present and timed (compile-warmup vs artifact load), the load
  path's ``artifact_compile_count == 0``, plus the chaos section's
  mid-stream-swap pins (positive ``post_swap_requests``,
  ``post_swap_version_ok`` true). From schema v5 on, the
  ``telemetry_overhead`` section (the ISSUE 12 unified telemetry
  plane) is required too: the PAIRED plane-on vs plane-off throughput
  with ``overhead_x <= 1.05`` (the <=5% bound is the leg's whole
  claim — an artifact recording a costlier plane must not land
  green), the exactly-once-span and zero-recompile pins re-checked,
  an SLO evaluation with at least one class, and a
  ``device_attribution`` record that either carries the profiler
  split fields or names WHY it has none (the CPU fallback). From
  schema v6 on, the ``continuous_batching`` section (the ISSUE 13
  learned-ladder leg) is required too: both paired legs (fixed-drain
  baseline vs continuous over the learned ladder) present with
  positive tails, the p95 improvement recorded, a non-empty learned
  rung list, and the abort-grade pins re-checked —
  ``recompiles_after_freeze == 0`` and exactly-once spans. From
  schema v7 on, the ``overload`` section (the ISSUE 14 elastic-
  serving leg) is required too: the autoscaled-vs-fixed fleet
  comparison present with attainment-per-replica-second recorded for
  every fleet, the beat re-checked NUMERICALLY (autoscaled strictly
  above every fixed fleet), interactive attainment held while batch
  shed, >= 1 scale-up, zero lost accepted requests, zero recompiles,
  exactly-once spans. From schema v8 on, the ``pod`` section (the
  ISSUE 15 cross-process serving leg) is required too: a worker pod
  of >= 2 processes, at least one SIGKILL and one network partition
  actually fired, zero lost accepted requests, exactly-once spans
  with the trace context propagated across the wire, and zero
  recompiles on every surviving worker.
- ``MULTICHIP_rNN.json`` — the dryrun wrapper: ``n_devices``/``rc``/
  ``ok``/``tail``, with ``ok`` true iff ``rc == 0`` (a disagreeing
  pair is exactly the silent-green failure this tool exists to catch).
- ``GRAFTLINT_rNN.json`` — ``python -m tools.graftlint --format json``
  output (the ISSUE 10 static-analysis gate): ``schema`` in the
  ``GRAFTLINT.`` family, the per-rule ``counts`` table covering every
  GL rule, ``findings`` EMPTY with ``clean`` true (a committed lint
  artifact carrying findings is the silent-red landing this gate
  exists to stop), and every ``suppressed`` entry carrying its
  mandatory reason — the audit trail that makes an inline disable an
  argued exception instead of a silence.
- ``CAMPAIGN_*.json`` — ``tools/run_campaign.py``'s scenario-fuzzing
  artifact (the ISSUE 16 campaign plane): ``schema`` in the
  ``CAMPAIGN.`` family, the campaign ``seed``, budget/scenario counts
  that agree (``scenarios == budget`` unless honestly ``truncated``),
  one verdict per scenario (parseable canonical spec string, schedule
  digest, ``ok`` consistent with its violation codes), a ``failures``
  count that matches the red verdicts, every violation's shrink trace
  well-formed, and — the committed-artifact contract — ZERO failures:
  a campaign artifact carrying violations is an unfixed bug wearing a
  green filename; the shrunk repro belongs in
  ``campaigns/regressions/`` next to its fix. From schema v2 on (the
  ISSUE 18 coverage-guided hunter), the hunt accounting is contract
  too: a ``coverage`` axis tally of non-negative ints, a
  ``wall_budget_s`` that is positive or honestly null, and per-verdict
  provenance — an ``origin`` that is either a grid draw (with its pool
  index) or a mutation (whose ``parent`` ran EARLIER in the verdict
  sequence, with its stream and attempt), plus the ``signature`` axis
  list the scheduler priced — so a hand-edited artifact can never wear
  a lineage the seed would not re-derive.
- ``SCALE_rNN.json`` — ``scale_bench.py``'s own artifact (the ISSUE 8
  cohort plane): ``schema`` in the ``SCALE.`` family, a ``platform``
  label, a non-empty ``records`` list, and — from schema v1 on — a
  ``cohort`` section for the million-client streamed leg: client/
  shard/round counts, positive throughput and wall time,
  ``streamed == true``, and ``recompiles_after_warmup == 0`` (ONE
  compiled shard-tier program covers every shard of every round —
  the streamed zero-recompile pin, re-checked here so a hand-edited
  artifact can never land green).

Exit status: 0 when every matched artifact validates, 1 otherwise
(problems listed one per line on stderr). No matches is an ERROR under
``--expect-some`` (the tier-1 invocation: the committed artifacts
exist, so finding none means the glob or cwd is wrong).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

#: Filename prefix -> validator. Order matters: BENCH_SERVE_ must be
#: tested before the BENCH_ prefix it also matches.
FAMILIES = ("BENCH_SERVE_", "BENCH_", "MULTICHIP_", "SCALE_",
            "GRAFTLINT_", "CAMPAIGN_")


def _tail_json_lines(tail: str) -> list[dict]:
    out = []
    for ln in tail.splitlines():
        ln = ln.strip()
        if ln.startswith("{") and ln.endswith("}"):
            try:
                out.append(json.loads(ln))
            except json.JSONDecodeError:
                continue
    return out


def check_bench_wrapper(art: dict, name: str) -> list[str]:
    """The driver wrapper around a bench.py run."""
    errs = []
    for key in ("rc", "tail"):
        if key not in art:
            errs.append(f"missing required field {key!r}")
    if "parsed" not in art:
        errs.append("missing required field 'parsed'")
        return errs
    parsed, rc = art["parsed"], art.get("rc")
    if rc == 0:
        if not isinstance(parsed, dict):
            errs.append("rc == 0 but 'parsed' is not the headline "
                        "record (driver failed to parse the final "
                        "JSON line?)")
            return errs
        for key in ("metric", "value", "unit"):
            if key not in parsed:
                errs.append(f"parsed headline missing {key!r}")
        if not isinstance(parsed.get("value"), (int, float)) \
                or parsed.get("value", 0) <= 0:
            errs.append(f"parsed headline value must be a positive "
                        f"number, got {parsed.get('value')!r}")
        # the platform label shipped with capture 2; r01 predates it
        # and is grandfathered BY NUMBER so the rule stays strict for
        # every artifact that could carry it
        legacy = art.get("n") == 1
        if "platform" not in parsed and not legacy:
            errs.append("parsed headline missing 'platform' label "
                        "(required from capture 2 on)")
        # headline-metric-LAST: the driver records the final JSON line
        lines = _tail_json_lines(art.get("tail", ""))
        if lines and lines[-1].get("metric") != parsed.get("metric"):
            errs.append(
                f"headline-metric-last violated: tail's final JSON "
                f"line is {lines[-1].get('metric')!r}, parsed is "
                f"{parsed.get('metric')!r}")
    elif parsed is not None and not isinstance(parsed, dict):
        errs.append(f"rc != 0: 'parsed' must be null or a record, "
                    f"got {type(parsed).__name__}")
    return errs


def check_serve_artifact(art: dict, name: str) -> list[str]:
    """serve_bench.py's own BENCH_SERVE.vN artifact."""
    errs = []
    schema = str(art.get("schema", ""))
    if not schema.startswith("BENCH_SERVE."):
        errs.append(f"schema must be in the BENCH_SERVE. family, "
                    f"got {art.get('schema')!r}")
    if "metric" not in art:
        errs.append("missing required field 'metric'")
    if not isinstance(art.get("platform"), str) or not art["platform"]:
        errs.append("missing top-level 'platform' label")
    buckets = art.get("bucket_latency")
    if not isinstance(buckets, dict) or not buckets:
        errs.append("'bucket_latency' must be a non-empty per-rung "
                    "table")
    else:
        for rung, rec in buckets.items():
            for q in ("p50_ms", "p99_ms"):
                if not isinstance(rec.get(q), (int, float)):
                    errs.append(f"bucket {rung}: missing {q}")
    stream = art.get("mixed_stream")
    if not isinstance(stream, dict) \
            or not isinstance(stream.get("requests"), int) \
            or stream["requests"] <= 0:
        errs.append("'mixed_stream' must record a positive request "
                    "count")
    if not isinstance(art.get("recompiles_after_warmup"), int):
        errs.append("missing 'recompiles_after_warmup' (the "
                    "zero-recompile pin reads it)")
    errs.extend(_check_rollout_section(art, schema))
    errs.extend(_check_chaos_section(art, schema))
    errs.extend(_check_cold_start_section(art, schema))
    errs.extend(_check_telemetry_section(art, schema))
    errs.extend(_check_continuous_section(art, schema))
    errs.extend(_check_overload_section(art, schema))
    errs.extend(_check_pod_section(art, schema))
    return errs


def _schema_version(schema: str) -> int | None:
    """The N of ``BENCH_SERVE.vN``, or None when unparseable (the
    caller reports that as its own error exactly once)."""
    try:
        return int(schema.rsplit(".v", 1)[1])
    except (IndexError, ValueError):
        return None


def _check_rollout_section(art: dict, schema: str) -> list[str]:
    """The v2+ ``rollout`` contract (the continuous-deployment leg):
    the driver reads swap latency, the in-flight tail across swaps,
    the canary and rollback-drill verdicts, and the swaps-recompile
    pin. v1 artifacts predate the leg (grandfathered by schema
    version, like the BENCH_ platform label by capture number)."""
    if not schema.startswith("BENCH_SERVE."):
        return []  # family error already reported by the caller
    version = _schema_version(schema)
    if version is None:
        # 'BENCH_SERVE.v2-rc1' etc. would otherwise skip the v2 rules
        # entirely — the silent-green landing this gate exists to stop
        return [f"unparseable schema version {schema!r} "
                "(expected BENCH_SERVE.vN)"]
    if version < 2:
        return []
    rollout = art.get("rollout")
    if not isinstance(rollout, dict):
        return ["schema v2+ requires a 'rollout' section (the "
                "continuous-deployment leg)"]
    errs = []
    if not isinstance(rollout.get("swaps"), int) or rollout["swaps"] < 1:
        errs.append("rollout: 'swaps' must be a positive int")
    for key in ("swap_p50_ms", "inflight_p95_ms"):
        if not isinstance(rollout.get(key), (int, float)):
            errs.append(f"rollout: missing numeric {key!r}")
    if not isinstance(rollout.get("recompiles_during_swaps"), int):
        errs.append("rollout: missing int 'recompiles_during_swaps' "
                    "(the hot-swap zero-recompile pin reads it)")
    for key in ("canary", "rollback_drill"):
        verdict = rollout.get(key)
        if not isinstance(verdict, str) or not verdict:
            errs.append(f"rollout: missing {key!r} verdict")
        elif verdict == "FAILED":
            # the bench aborts on these; an artifact carrying one is
            # exactly the silent-green failure this tool catches
            errs.append(f"rollout: {key} == 'FAILED' must never land "
                        "in a committed artifact")
    if "final_version" not in rollout \
            or not isinstance(rollout.get("staleness_rounds"), int):
        errs.append("rollout: missing 'final_version'/"
                    "'staleness_rounds' dimensions")
    return errs


def _check_chaos_section(art: dict, schema: str) -> list[str]:
    """The v3+ ``chaos`` contract (the replica-fleet failover leg):
    the driver reads the kill/requeue/hedge counters and the tail with
    vs without chaos, and the abort-grade pins (zero lost requests,
    zero recompiles across kills/failovers, at least one kill actually
    fired, every span accounted exactly once) are re-checked here — a
    hand-edited or drifted artifact must not land green. Earlier
    schema versions predate the leg and are grandfathered."""
    if not schema.startswith("BENCH_SERVE."):
        return []  # family error already reported by the caller
    version = _schema_version(schema)
    if version is None:
        return []  # the rollout check already reported it
    if version < 3:
        return []
    chaos = art.get("chaos")
    if not isinstance(chaos, dict):
        return ["schema v3+ requires a 'chaos' section (the "
                "replica-fleet failover leg)"]
    errs = []
    for key in ("replicas", "requests", "kills_observed", "requeues",
                "hedge_wins"):
        if not isinstance(chaos.get(key), int) or chaos[key] < 0:
            errs.append(f"chaos: {key!r} must be a non-negative int")
    if isinstance(chaos.get("requests"), int) and chaos["requests"] < 1:
        errs.append("chaos: 'requests' must be positive")
    if isinstance(chaos.get("kills_observed"), int) \
            and chaos["kills_observed"] < 1:
        errs.append("chaos: 'kills_observed' must be >= 1 (a chaos "
                    "leg that never exercised failover proves nothing)")
    for key in ("p95_ms_clean", "p95_ms_chaos"):
        if not isinstance(chaos.get(key), (int, float)):
            errs.append(f"chaos: missing numeric {key!r} (the tail "
                        "with vs without chaos)")
    if chaos.get("lost") != 0:
        errs.append(f"chaos: lost={chaos.get('lost')!r} — every "
                    "accepted request must resolve; a committed "
                    "artifact may never carry lost requests")
    if chaos.get("recompiles_during_chaos") != 0:
        errs.append("chaos: recompiles_during_chaos="
                    f"{chaos.get('recompiles_during_chaos')!r} — the "
                    "fleet shares ONE compiled ladder; failover must "
                    "never recompile")
    if chaos.get("spans_exactly_once") is not True:
        errs.append("chaos: 'spans_exactly_once' must be true (every "
                    "accepted request id lands one span)")
    return errs


def _check_cold_start_section(art: dict, schema: str) -> list[str]:
    """The v4+ ``cold_start`` contract (the AOT-artifact leg): BOTH
    replica start modes must be present and timed (compile-warmup
    start vs artifact-load start), and the abort-grade pin — the
    artifact path came up and served with ``compile_count == 0`` — is
    re-checked here so a hand-edited artifact can never land a
    compiled "cold start" as an AOT one. v4 also extends the chaos
    section with the mid-stream-swap pins (chaos-under-rollout).
    Earlier schema versions predate the leg and are grandfathered."""
    if not schema.startswith("BENCH_SERVE."):
        return []  # family error already reported by the caller
    version = _schema_version(schema)
    if version is None:
        return []  # the rollout check already reported it
    if version < 4:
        return []
    cold = art.get("cold_start")
    if not isinstance(cold, dict):
        errs = ["schema v4+ requires a 'cold_start' section (the "
                "AOT-artifact leg)"]
    else:
        errs = []
        # both start modes, timed: a section with only one mode never
        # made the comparison the leg exists for
        for key in ("compile_warmup_s", "artifact_load_s",
                    "artifact_export_s"):
            if not isinstance(cold.get(key), (int, float)) \
                    or cold[key] <= 0:
                errs.append(f"cold_start: missing positive numeric "
                            f"{key!r} (both start modes must be "
                            "present and timed)")
        if cold.get("artifact_compile_count") != 0:
            errs.append("cold_start: artifact_compile_count="
                        f"{cold.get('artifact_compile_count')!r} — "
                        "the artifact load path must compile NOTHING; "
                        "a nonzero count is a compiled start wearing "
                        "the AOT label")
        if not isinstance(cold.get("rungs"), int) or cold["rungs"] < 1:
            errs.append("cold_start: 'rungs' must be a positive int")
    # the v4 chaos extension: the mid-stream swap actually happened
    # and every post-swap span carried the new version
    chaos = art.get("chaos")
    if isinstance(chaos, dict):
        if not isinstance(chaos.get("post_swap_requests"), int) \
                or chaos["post_swap_requests"] < 1:
            errs.append("chaos: v4 requires a positive "
                        "'post_swap_requests' (the mid-stream swap "
                        "must actually precede some requests)")
        if chaos.get("post_swap_version_ok") is not True:
            errs.append("chaos: 'post_swap_version_ok' must be true "
                        "(every post-swap span carries the new "
                        "model_version)")
    return errs


def _check_telemetry_section(art: dict, schema: str) -> list[str]:
    """The v5+ ``telemetry_overhead`` contract (the ISSUE 12 unified
    telemetry plane): the PAIRED plane-on/plane-off comparison must be
    present, positive, and within the <=5% bound the leg exists to
    prove; the exactly-once-span and zero-recompile pins are
    re-checked at the gate (a hand-edited artifact must not land
    green); the SLO evaluation must cover at least one class; and the
    device-attribution record must either carry the profiler split
    fields or name why it has none (the graceful CPU fallback).
    Earlier schema versions predate the leg and are grandfathered."""
    if not schema.startswith("BENCH_SERVE."):
        return []  # family error already reported by the caller
    version = _schema_version(schema)
    if version is None:
        return []  # the rollout check already reported it
    if version < 5:
        return []
    tel = art.get("telemetry_overhead")
    if not isinstance(tel, dict):
        return ["schema v5+ requires a 'telemetry_overhead' section "
                "(the unified telemetry plane leg)"]
    errs = []
    ox = tel.get("overhead_x")
    if not isinstance(ox, (int, float)) or ox <= 0:
        errs.append("telemetry_overhead: 'overhead_x' must be a "
                    "positive number")
    elif ox > 1.05:
        errs.append(f"telemetry_overhead: overhead_x={ox} exceeds the "
                    "1.05 bound — the plane's whole claim is <=5% "
                    "cost; a costlier capture must not land green")
    if not isinstance(tel.get("reps"), int) or tel["reps"] < 1:
        errs.append("telemetry_overhead: 'reps' must be a positive "
                    "int (the paired best-of estimator's sample size)")
    for key in ("plane_on_req_per_s", "plane_off_req_per_s"):
        if not isinstance(tel.get(key), (int, float)) or tel[key] <= 0:
            errs.append(f"telemetry_overhead: missing positive "
                        f"numeric {key!r} (both paired legs must be "
                        "measured)")
    if tel.get("spans_exactly_once") is not True:
        errs.append("telemetry_overhead: 'spans_exactly_once' must be "
                    "true (the exactly-once pin stays abort-grade "
                    "under the full plane)")
    if tel.get("recompiles_during_telemetry") != 0:
        errs.append("telemetry_overhead: recompiles_during_telemetry="
                    f"{tel.get('recompiles_during_telemetry')!r} — "
                    "observability must never perturb the shape "
                    "discipline")
    slo = tel.get("slo")
    if not isinstance(slo, dict) or not isinstance(
            slo.get("classes"), dict) or not slo["classes"]:
        errs.append("telemetry_overhead: 'slo' must record a per-class "
                    "evaluation with at least one class")
    attr = tel.get("device_attribution")
    if not isinstance(attr, dict) or "source" not in attr:
        errs.append("telemetry_overhead: 'device_attribution' must be "
                    "a record naming its 'source'")
    elif attr["source"] == "profiler":
        # the split landed: its fields are contract
        for key in ("device_compute_s", "xla_queue_s"):
            if not isinstance(attr.get(key), (int, float)):
                errs.append(f"telemetry_overhead: profiler attribution "
                            f"missing numeric {key!r}")
        frac = attr.get("compute_fraction")
        if not isinstance(frac, (int, float)) or not 0 <= frac <= 1:
            errs.append("telemetry_overhead: profiler attribution "
                        "'compute_fraction' must be in [0, 1]")
    elif not attr.get("reason"):
        errs.append("telemetry_overhead: a non-profiler "
                    "device_attribution must carry its 'reason' (the "
                    "honest CPU-fallback shape)")
    return errs


def _check_continuous_section(art: dict, schema: str) -> list[str]:
    """The v6+ ``continuous_batching`` contract (the ISSUE 13
    learned-ladder continuous-batching leg): BOTH paired legs must be
    present and measured (fixed-drain baseline vs continuous over the
    learned ladder, each with a positive p95 on a positive request
    count), the p95 improvement must be recorded, the learned ladder
    must be a non-empty rung list, and the abort-grade pins are
    re-checked at the gate: zero recompiles after ladder freeze and
    exactly-once spans (a hand-edited artifact must not land green).
    Earlier schema versions predate the leg and are grandfathered."""
    if not schema.startswith("BENCH_SERVE."):
        return []  # family error already reported by the caller
    version = _schema_version(schema)
    if version is None:
        return []  # the rollout check already reported it
    if version < 6:
        return []
    cb = art.get("continuous_batching")
    if not isinstance(cb, dict):
        return ["schema v6+ requires a 'continuous_batching' section "
                "(the learned-ladder continuous-batching leg)"]
    errs = []
    for leg in ("baseline", "continuous"):
        rec = cb.get(leg)
        if not isinstance(rec, dict):
            errs.append(f"continuous_batching: missing paired "
                        f"{leg!r} leg record")
            continue
        if not isinstance(rec.get("requests"), int) \
                or rec["requests"] < 1:
            errs.append(f"continuous_batching: {leg} leg must record "
                        "a positive request count")
        for key in ("p50_ms", "p95_ms"):
            if not isinstance(rec.get(key), (int, float)) \
                    or rec[key] <= 0:
                errs.append(f"continuous_batching: {leg} leg missing "
                            f"positive numeric {key!r}")
    imp = cb.get("p95_improvement_x")
    if not isinstance(imp, (int, float)) or imp <= 0:
        errs.append("continuous_batching: 'p95_improvement_x' must be "
                    "a positive number (the paired comparison is the "
                    "leg's whole claim)")
    ladder = cb.get("ladder")
    if not isinstance(ladder, dict) \
            or not isinstance(ladder.get("learned"), list) \
            or not ladder["learned"]:
        errs.append("continuous_batching: 'ladder.learned' must be a "
                    "non-empty rung list")
    if cb.get("recompiles_after_freeze") != 0:
        errs.append("continuous_batching: recompiles_after_freeze="
                    f"{cb.get('recompiles_after_freeze')!r} — "
                    "re-bucketing must never compile on the hot path "
                    "after the learner froze")
    if cb.get("spans_exactly_once") is not True:
        errs.append("continuous_batching: 'spans_exactly_once' must "
                    "be true (every accepted request id lands one "
                    "span under continuous admission)")
    return errs


def _check_overload_section(art: dict, schema: str) -> list[str]:
    """The v7+ ``overload`` contract (the ISSUE 14 elastic-serving
    leg): the autoscaled-vs-fixed fleet comparison must be PRESENT
    (an ``autoscaled`` record plus at least one ``fixed_*`` record,
    each with attainment-per-replica-second — positive replica-
    seconds and a recorded ``good_per_replica_s``), and the
    abort-grade pins are re-checked numerically at the gate: the
    autoscaled fleet's good-per-replica-second strictly exceeds EVERY
    fixed fleet's (the leg's whole claim — a hand-edited artifact
    where it doesn't must not land green), interactive attainment
    held its objective while batch shed, at least one scale-up fired,
    zero lost accepted requests, zero recompiles, exactly-once spans.
    Earlier schema versions predate the leg and are grandfathered."""
    if not schema.startswith("BENCH_SERVE."):
        return []  # family error already reported by the caller
    version = _schema_version(schema)
    if version is None:
        return []  # the rollout check already reported it
    if version < 7:
        return []
    ov = art.get("overload")
    if not isinstance(ov, dict):
        return ["schema v7+ requires an 'overload' section (the "
                "elastic-serving leg)"]
    errs = []
    fleets = ov.get("fleets")
    if not isinstance(fleets, dict) or "autoscaled" not in fleets \
            or not any(k.startswith("fixed_") for k in fleets):
        return errs + ["overload: 'fleets' must record the autoscaled "
                       "fleet AND at least one fixed_* comparator"]
    for name, rec in fleets.items():
        if not isinstance(rec, dict):
            errs.append(f"overload: fleet {name!r} must be a record")
            continue
        if not isinstance(rec.get("requests"), int) \
                or rec["requests"] < 1:
            errs.append(f"overload: fleet {name} must record a "
                        "positive request count")
        if not isinstance(rec.get("replica_seconds"), (int, float)) \
                or rec["replica_seconds"] <= 0:
            errs.append(f"overload: fleet {name} missing positive "
                        "'replica_seconds' (the comparison's "
                        "denominator)")
        if not isinstance(rec.get("good_per_replica_s"), (int, float)):
            errs.append(f"overload: fleet {name} missing numeric "
                        "'good_per_replica_s' (attainment per "
                        "replica-second)")
        if rec.get("lost") != 0:
            errs.append(f"overload: fleet {name} lost="
                        f"{rec.get('lost')!r} — every accepted "
                        "request must resolve typed; a committed "
                        "artifact may never carry lost requests")
    auto = fleets.get("autoscaled")
    if isinstance(auto, dict) and isinstance(
            auto.get("good_per_replica_s"), (int, float)):
        for name, rec in fleets.items():
            if name == "autoscaled" or not isinstance(rec, dict):
                continue
            g = rec.get("good_per_replica_s")
            if isinstance(g, (int, float)) \
                    and auto["good_per_replica_s"] <= g:
                errs.append(
                    f"overload: autoscaled good_per_replica_s="
                    f"{auto['good_per_replica_s']} must beat {name}'s "
                    f"{g} — the elastic fleet's whole claim")
        if not isinstance(auto.get("scale_ups"), int) \
                or auto["scale_ups"] < 1:
            errs.append("overload: autoscaled 'scale_ups' must be "
                        ">= 1 (a leg where the autoscaler never acted "
                        "proves nothing)")
    if ov.get("autoscaled_beats_every_fixed") is not True:
        errs.append("overload: 'autoscaled_beats_every_fixed' must "
                    "be true")
    if ov.get("interactive_attainment_ok") is not True:
        errs.append("overload: 'interactive_attainment_ok' must be "
                    "true (interactive holds its objective while "
                    "batch sheds)")
    if not isinstance(ov.get("batch_shed"), int) \
            or ov["batch_shed"] < 1:
        errs.append("overload: 'batch_shed' must be >= 1 (class-aware "
                    "shedding must actually have shed the batch "
                    "class)")
    if ov.get("lost_accepted") != 0:
        errs.append(f"overload: lost_accepted="
                    f"{ov.get('lost_accepted')!r} must be 0")
    if ov.get("recompiles_during_overload") != 0:
        errs.append("overload: recompiles_during_overload="
                    f"{ov.get('recompiles_during_overload')!r} — "
                    "scale-out rides the AOT artifact plane; an "
                    "elastic fleet must never compile")
    if ov.get("spans_exactly_once") is not True:
        errs.append("overload: 'spans_exactly_once' must be true "
                    "(every submitted request id — shed ones "
                    "included — lands one span)")
    return errs


def _check_pod_section(art: dict, schema: str) -> list[str]:
    """The v8+ ``pod`` contract (the ISSUE 15 cross-process serving
    leg): a multi-process worker pod must have been exercised for
    real — at least two workers, at least one SIGKILL and one network
    partition actually FIRED (a pod leg whose chaos never fired
    proves nothing) — and the abort-grade pins are re-checked at the
    gate so a hand-edited artifact can never land green: zero lost
    accepted requests, exactly-once request spans with the trace
    context propagated across the wire, and zero recompiles on every
    surviving worker (the pod rides the AOT artifact plane). Earlier
    schema versions predate the leg and are grandfathered."""
    if not schema.startswith("BENCH_SERVE."):
        return []  # family error already reported by the caller
    version = _schema_version(schema)
    if version is None:
        return []  # the rollout check already reported it
    if version < 8:
        return []
    pod = art.get("pod")
    if not isinstance(pod, dict):
        return ["schema v8+ requires a 'pod' section (the "
                "cross-process serving leg)"]
    errs = []
    if not isinstance(pod.get("workers"), int) or pod["workers"] < 2:
        errs.append("pod: 'workers' must be an int >= 2 (one process "
                    "is not a pod)")
    if not isinstance(pod.get("requests"), int) or pod["requests"] < 1:
        errs.append("pod: 'requests' must be a positive int")
    if not isinstance(pod.get("kills_fired"), int) \
            or pod["kills_fired"] < 1:
        errs.append("pod: 'kills_fired' must be >= 1 (a pod leg that "
                    "never killed a worker process proves nothing)")
    if not isinstance(pod.get("partitions_fired"), int) \
            or pod["partitions_fired"] < 1:
        errs.append("pod: 'partitions_fired' must be >= 1 (a pod leg "
                    "that never partitioned a route proves nothing)")
    if pod.get("lost") != 0:
        errs.append(f"pod: lost={pod.get('lost')!r} — every accepted "
                    "request must resolve typed across the wire; a "
                    "committed artifact may never carry lost requests")
    if pod.get("spans_exactly_once") is not True:
        errs.append("pod: 'spans_exactly_once' must be true (every "
                    "accepted request id lands one span, worker "
                    "deaths included)")
    if pod.get("trace_propagated") is not True:
        errs.append("pod: 'trace_propagated' must be true (worker-"
                    "side spans must carry router-sent trace ids — "
                    "the TRACECTX.v1 cross-process contract)")
    if pod.get("survivor_recompiles") != 0:
        errs.append("pod: survivor_recompiles="
                    f"{pod.get('survivor_recompiles')!r} — workers "
                    "load the AOT artifact; a surviving worker must "
                    "never compile")
    return errs


def check_multichip(art: dict, name: str) -> list[str]:
    """The dryrun_multichip wrapper."""
    errs = []
    for key in ("n_devices", "rc", "ok", "tail"):
        if key not in art:
            errs.append(f"missing required field {key!r}")
    if "rc" in art and "ok" in art and art["ok"] != (art["rc"] == 0):
        errs.append(f"ok={art['ok']!r} disagrees with rc={art['rc']!r} "
                    "(silent-green hazard)")
    if art.get("ok") and "OK" not in art.get("tail", ""):
        errs.append("ok == true but the tail carries no 'OK' verdict "
                    "line")
    return errs


def check_scale_artifact(art: dict, name: str) -> list[str]:
    """scale_bench.py's own SCALE.vN artifact (the cohort plane)."""
    errs = []
    schema = str(art.get("schema", ""))
    if not schema.startswith("SCALE."):
        errs.append(f"schema must be in the SCALE. family, "
                    f"got {art.get('schema')!r}")
        return errs
    if not isinstance(art.get("platform"), str) or not art["platform"]:
        errs.append("missing top-level 'platform' label")
    records = art.get("records")
    if not isinstance(records, list) or not records:
        errs.append("'records' must be a non-empty list of per-config "
                    "records")
    else:
        for i, rec in enumerate(records):
            if not isinstance(rec, dict) or "config" not in rec:
                errs.append(f"records[{i}]: missing 'config' label")
            elif not isinstance(rec.get("wall_s"), (int, float)) \
                    or rec["wall_s"] <= 0:
                errs.append(f"records[{i}] ({rec['config']}): "
                            "missing positive 'wall_s'")
    try:
        version = int(schema.rsplit(".v", 1)[1])
    except (IndexError, ValueError):
        # 'SCALE.v1-rc1' etc. would otherwise skip the cohort rules
        # entirely — the silent-green landing this gate exists to stop
        return errs + [f"unparseable schema version {schema!r} "
                       "(expected SCALE.vN)"]
    if version < 1:
        return errs
    cohort = art.get("cohort")
    if not isinstance(cohort, dict):
        return errs + ["schema v1+ requires a 'cohort' section (the "
                       "million-client streamed leg)"]
    for key in ("clients", "shards", "shard_clients", "rounds"):
        if not isinstance(cohort.get(key), int) or cohort[key] < 1:
            errs.append(f"cohort: {key!r} must be a positive int")
    if isinstance(cohort.get("shards"), int) and cohort["shards"] < 2:
        errs.append("cohort: 'shards' must be >= 2 (a one-shard "
                    "cohort never exercised the two-tier fold)")
    for key in ("updates_per_sec", "wall_s"):
        if not isinstance(cohort.get(key), (int, float)) \
                or cohort[key] <= 0:
            errs.append(f"cohort: missing positive numeric {key!r}")
    if cohort.get("streamed") is not True:
        errs.append("cohort: 'streamed' must be true (the leg exists "
                    "to certify the host->device streamed tier)")
    if cohort.get("recompiles_after_warmup") != 0:
        errs.append("cohort: recompiles_after_warmup="
                    f"{cohort.get('recompiles_after_warmup')!r} — one "
                    "compiled shard-tier program must cover every "
                    "shard of every round")
    return errs


def check_graftlint_artifact(art: dict, name: str) -> list[str]:
    """``tools.graftlint --format json`` output (GRAFTLINT.vN)."""
    errs = []
    schema = str(art.get("schema", ""))
    if not schema.startswith("GRAFTLINT."):
        errs.append(f"schema must be in the GRAFTLINT. family, "
                    f"got {art.get('schema')!r}")
        return errs
    try:
        int(schema.rsplit(".v", 1)[1])
    except (IndexError, ValueError):
        errs.append(f"unparseable schema version {schema!r} "
                    "(expected GRAFTLINT.vN)")
    counts = art.get("counts")
    if not isinstance(counts, dict) or not counts:
        errs.append("'counts' must be the per-rule finding table")
    else:
        for rule, n in counts.items():
            if not isinstance(n, int) or n < 0:
                errs.append(f"counts[{rule}]: must be a non-negative "
                            "int")
    findings = art.get("findings")
    if isinstance(counts, dict) and isinstance(findings, list) and \
            sum(n for n in counts.values()
                if isinstance(n, int)) != len(findings):
        # a self-contradicting artifact (counts say 7, findings say
        # none) must not validate — the table and the list are two
        # views of ONE result
        errs.append(f"counts total {sum(counts.values())!r} "
                    f"disagrees with {len(findings)} finding(s)")
    rules_run = art.get("rules_run")
    if rules_run is not None:
        if not isinstance(rules_run, list) or not rules_run:
            errs.append("'rules_run' must be a non-empty list of the "
                        "rules this run executed")
        elif isinstance(counts, dict) and \
                set(counts) != set(map(str, rules_run)):
            # a partial (--rules) run must not wear a full run's
            # counts table
            errs.append("counts keys disagree with 'rules_run' — a "
                        "partial run must not read as full coverage")
    if not isinstance(findings, list):
        errs.append("'findings' must be a list")
    elif findings or art.get("clean") is not True:
        # the committed-artifact contract: a lint artifact may only
        # land CLEAN — findings belong in the PR that fixes them, not
        # in a green-looking JSON nobody reads
        errs.append(f"{len(findings or [])} finding(s) with "
                    f"clean={art.get('clean')!r} — a committed "
                    "graftlint artifact must be clean")
    for section in ("suppressed", "baselined"):
        entries = art.get(section)
        if not isinstance(entries, list):
            errs.append(f"'{section}' must be a list")
            continue
        for i, rec in enumerate(entries):
            if not isinstance(rec, dict) or not all(
                    k in rec for k in ("rule", "path", "line",
                                       "fingerprint")):
                errs.append(f"{section}[{i}]: missing "
                            "rule/path/line/fingerprint")
            elif section == "suppressed" and not rec.get("reason"):
                errs.append(f"{section}[{i}]: suppression without a "
                            "reason (the inline-disable contract "
                            "requires one)")
    return errs


def _check_hunt_verdict(v: dict, i: int) -> list[str]:
    """The CAMPAIGN.v2 per-verdict provenance contract: every record
    names where the scheduler got it (a grid draw or a mutation of an
    EARLIER verdict) and which coverage axes it actually touched — the
    facts the search digest hashes, so a record without them cannot be
    replayed."""
    errs = []
    origin = v.get("origin")
    if not isinstance(origin, dict):
        errs.append("schema v2+ requires an 'origin' record (grid "
                    "draw or mutation lineage)")
    elif origin.get("kind") == "grid":
        if not isinstance(origin.get("index"), int) \
                or origin["index"] < 0:
            errs.append("grid origin must carry its non-negative "
                        "pool 'index'")
    elif origin.get("kind") == "mutation":
        parent = origin.get("parent")
        if not isinstance(parent, int) or not 0 <= parent < i:
            errs.append(f"mutation origin 'parent'={parent!r} must "
                        "name an EARLIER verdict index (lineage is "
                        "well-founded: the near-miss ran first)")
        if not isinstance(origin.get("stream"), str) \
                or not origin.get("stream"):
            errs.append("mutation origin must name the re-keyed "
                        "'stream'")
        if not isinstance(origin.get("attempt"), int) \
                or origin["attempt"] < 1:
            errs.append("mutation origin 'attempt' must be a "
                        "positive int")
    else:
        errs.append(f"origin kind {origin.get('kind')!r} must be "
                    "'grid' or 'mutation'")
    sig = v.get("signature")
    if not isinstance(sig, list) \
            or not all(isinstance(a, str) and a for a in sig):
        errs.append("schema v2+ requires a 'signature' list of axis "
                    "names (the coverage facts the digest hashes)")
    return errs


def _check_hunt_accounting(art: dict) -> list[str]:
    """The CAMPAIGN.v2 top-level hunt accounting: the coverage tally
    that steered the scheduler, and the wall budget the run was
    honest about."""
    errs = []
    cov = art.get("coverage")
    if not isinstance(cov, dict) or not cov:
        errs.append("schema v2+ requires a non-empty 'coverage' axis "
                    "tally (the rarity scheduler's steering state)")
    else:
        for axis, n in cov.items():
            if not isinstance(n, int) or n < 0:
                errs.append(f"coverage[{axis}]: must be a "
                            "non-negative int")
    if "wall_budget_s" not in art:
        errs.append("schema v2+ requires 'wall_budget_s' (positive "
                    "number, or null for an uncapped hunt)")
    else:
        wall = art["wall_budget_s"]
        if wall is not None and (not isinstance(wall, (int, float))
                                 or wall <= 0):
            errs.append(f"'wall_budget_s'={wall!r} must be a positive "
                        "number or null")
    return errs


def check_campaign_artifact(art: dict, name: str) -> list[str]:
    """``tools/run_campaign.py``'s CAMPAIGN.vN artifact (the scenario
    fuzzing plane)."""
    errs = []
    schema = str(art.get("schema", ""))
    if not schema.startswith("CAMPAIGN."):
        errs.append(f"schema must be in the CAMPAIGN. family, "
                    f"got {art.get('schema')!r}")
        return errs
    version = _schema_version(schema)
    if version is None:
        errs.append(f"unparseable schema version {schema!r} "
                    "(expected CAMPAIGN.vN)")
    if not isinstance(art.get("seed"), int) or art["seed"] < 0:
        errs.append("'seed' must be a non-negative int (the campaign "
                    "master everything derives from)")
    budget = art.get("budget")
    scenarios = art.get("scenarios")
    if not isinstance(budget, int) or budget < 1:
        errs.append("'budget' must be a positive int")
    if not isinstance(scenarios, int) or scenarios < 1:
        errs.append("'scenarios' must be a positive int")
    elif isinstance(budget, int):
        if scenarios > budget:
            errs.append(f"scenarios={scenarios} exceeds budget="
                        f"{budget}")
        elif scenarios < budget and art.get("truncated") is not True:
            # a short campaign must say WHY it is short — a silently
            # partial sweep reads as full coverage
            errs.append(f"scenarios={scenarios} < budget={budget} "
                        "without truncated=true")
    digest = art.get("digest")
    if not (isinstance(digest, str) and len(digest) == 64
            and all(c in "0123456789abcdef" for c in digest)):
        errs.append("'digest' must be the sha256 hex of the verdict "
                    "sequence (the same-seed bitwise pin compares it)")
    verdicts = art.get("verdicts")
    red = 0
    if not isinstance(verdicts, list):
        errs.append("'verdicts' must be a list (one record per "
                    "scenario run)")
    else:
        if isinstance(scenarios, int) and len(verdicts) != scenarios:
            errs.append(f"{len(verdicts)} verdict(s) disagree with "
                        f"scenarios={scenarios}")
        for i, v in enumerate(verdicts):
            if not isinstance(v, dict):
                errs.append(f"verdicts[{i}]: must be a record")
                continue
            spec = v.get("spec")
            if not isinstance(spec, str) or "seed=" not in spec:
                errs.append(f"verdicts[{i}]: 'spec' must be the "
                            "canonical scenario string")
            if not isinstance(v.get("digest"), str) or not v["digest"]:
                errs.append(f"verdicts[{i}]: missing schedule "
                            "'digest'")
            codes = v.get("codes")
            if not isinstance(codes, list):
                errs.append(f"verdicts[{i}]: 'codes' must be a list")
            elif v.get("ok") is not (not codes):
                # ok and codes are two views of ONE verdict
                errs.append(f"verdicts[{i}]: ok={v.get('ok')!r} "
                            f"disagrees with codes={codes!r}")
            if not v.get("ok", True):
                red += 1
            if version is not None and version >= 2:
                errs.extend(f"verdicts[{i}]: {e}"
                            for e in _check_hunt_verdict(v, i))
    if version is not None and version >= 2:
        errs.extend(_check_hunt_accounting(art))
    violations = art.get("violations")
    if not isinstance(violations, list):
        errs.append("'violations' must be a list (the failing "
                    "scenarios, with shrink traces)")
    else:
        if art.get("failures") != len(violations):
            errs.append(f"failures={art.get('failures')!r} disagrees "
                        f"with {len(violations)} violation record(s)")
        if isinstance(verdicts, list) and len(violations) != red:
            errs.append(f"{len(violations)} violation record(s) "
                        f"disagree with {red} red verdict(s)")
        for i, rec in enumerate(violations):
            if not isinstance(rec, dict) \
                    or not isinstance(rec.get("index"), int) \
                    or not isinstance(rec.get("verdict"), dict):
                errs.append(f"violations[{i}]: must carry its "
                            "scenario 'index' and 'verdict' record")
                continue
            shrunk = rec.get("shrunk")
            if shrunk is None:
                continue  # --no-shrink triage sweeps are honest
            if not isinstance(shrunk, dict) \
                    or not isinstance(shrunk.get("spec"), str) \
                    or not shrunk.get("codes") \
                    or not isinstance(shrunk.get("trace"), list):
                errs.append(f"violations[{i}]: 'shrunk' must carry "
                            "spec/codes/trace (the minimal repro and "
                            "how it was reached)")
                continue
            for j, step in enumerate(shrunk["trace"]):
                if not isinstance(step, dict) or not all(
                        k in step for k in ("action", "spec", "kept")):
                    errs.append(f"violations[{i}].trace[{j}]: missing "
                                "action/spec/kept")
    if not isinstance(art.get("wall_s"), (int, float)) \
            or art["wall_s"] < 0:
        errs.append("missing non-negative numeric 'wall_s'")
    if art.get("failures") != 0:
        # the committed-artifact contract (the graftlint precedent): a
        # campaign artifact may only land CLEAN — a violation belongs
        # in campaigns/regressions/ next to the commit that fixes it
        errs.append(f"failures={art.get('failures')!r} — a committed "
                    "campaign artifact must be clean; shrunk repros "
                    "belong in campaigns/regressions/ with their fix")
    return errs


CHECKERS = {
    "BENCH_SERVE_": check_serve_artifact,
    "BENCH_": check_bench_wrapper,
    "MULTICHIP_": check_multichip,
    "SCALE_": check_scale_artifact,
    "GRAFTLINT_": check_graftlint_artifact,
    "CAMPAIGN_": check_campaign_artifact,
}


def validate_file(path: str) -> list[str]:
    """All contract violations for one artifact (empty == valid)."""
    name = os.path.basename(path)
    try:
        with open(path) as f:
            art = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return [f"unreadable/not JSON: {e}"]
    if not isinstance(art, dict):
        return [f"top level must be an object, got "
                f"{type(art).__name__}"]
    for prefix in FAMILIES:
        if name.startswith(prefix):
            return CHECKERS[prefix](art, name)
    return [f"no schema family matches {name!r}"]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="validate committed bench artifacts against the "
                    "driver contract")
    ap.add_argument("paths", nargs="*",
                    help="artifact files to check (default: every "
                         "BENCH_*/BENCH_SERVE_*/MULTICHIP_* JSON under "
                         "--root)")
    ap.add_argument("--root", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))),
        help="repo root to glob when no paths are given")
    ap.add_argument("--expect-some", action="store_true",
                    help="fail when no artifact matches (the tier-1 "
                         "invocation: committed artifacts exist)")
    args = ap.parse_args(argv)

    paths = args.paths or sorted(
        p for prefix in FAMILIES
        for p in glob.glob(os.path.join(args.root, f"{prefix}*.json")))
    # the glob above matches BENCH_SERVE twice (its own prefix and the
    # BENCH_ one); validate each file once
    paths = sorted(set(paths))
    if not paths:
        if args.expect_some:
            print("check_bench_schema: no artifacts matched "
                  f"(root={args.root!r})", file=sys.stderr)
            return 1
        print("check_bench_schema: nothing to check")
        return 0
    bad = 0
    for path in paths:
        errs = validate_file(path)
        if errs:
            bad += 1
            for e in errs:
                print(f"{os.path.basename(path)}: {e}", file=sys.stderr)
        else:
            print(f"{os.path.basename(path)}: OK")
    if bad:
        print(f"check_bench_schema: {bad}/{len(paths)} artifact(s) "
              "violate the driver contract", file=sys.stderr)
        return 1
    print(f"check_bench_schema: {len(paths)} artifact(s) OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
