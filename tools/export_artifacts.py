#!/usr/bin/env python
"""Export a checkpoint's serving bucket ladder as an AOT artifact.

The operator half of the cold-start plane (``serving/artifacts.py``):
point it at a ``save_checkpoint`` directory and it builds the engine,
compiles every rung of the bucket ladder ONCE, and serializes the
ladder (portable ``jax.export`` programs + native executables + the
``ArtifactManifest`` host fingerprint) into OUT_DIR. A replica fleet
then cold-starts via ``ServingEngine.from_artifact(OUT_DIR,
checkpoint=CKPT)`` in load-milliseconds with ``compile_count == 0``,
instead of each replica paying compile-warmup seconds.

Usage:
    python tools/export_artifacts.py CKPT_DIR OUT_DIR \
        [--buckets 1,8,64,512,4096] [--model auto] [--input-dim N] \
        [--feature-dtype DT] [--round N] [--version N] [--check]

``--check`` immediately round-trips the artifact on this host:
``from_artifact`` + one dispatch per rung, verifying logits match the
compiled engine bitwise and that the load path compiled nothing — the
same pins the serve bench's ``cold_start`` leg enforces. The summary
line on stdout is JSON (rungs, bytes, timings, fingerprint) so a
deploy script can parse it.

Exit status: 0 on success; 1 on export/check failure (including a
typed ``ArtifactIncompatible`` — which here can only mean the host
changed between export and check).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="export a checkpoint's serving bucket ladder as "
                    "an AOT cold-start artifact")
    ap.add_argument("checkpoint", help="save_checkpoint directory")
    ap.add_argument("out_dir", help="artifact directory to write")
    ap.add_argument("--buckets", default=None,
                    help="comma-separated bucket ladder (default: the "
                         "engine default 1,8,64,512,4096)")
    ap.add_argument("--model", default="auto",
                    help="model zoo name (default: infer from the "
                         "checkpoint's parameter pytree)")
    ap.add_argument("--input-dim", type=int, default=None,
                    help="raw feature width (conv checkpoints only — "
                         "not inferable from the pytree)")
    ap.add_argument("--feature-dtype", default=None,
                    help="feature dtype of the training run "
                         "(prepare_setup(feature_dtype=...)); the "
                         "checkpoint's own marker wins when present")
    ap.add_argument("--round", type=int, default=None, dest="round_idx",
                    help="training round to stamp as provenance "
                         "(default: the checkpoint's own marker)")
    ap.add_argument("--version", type=int, default=None,
                    help="registry model version to stamp as provenance")
    ap.add_argument("--check", action="store_true",
                    help="round-trip the artifact after export: "
                         "from_artifact + one dispatch per rung, "
                         "bitwise parity vs the compiled engine, "
                         "compile_count == 0")
    args = ap.parse_args(argv)

    # same prologue as the bench drivers: honor JAX_PLATFORMS over the
    # container's sitecustomize before the first backend query
    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    from bench_common import reapply_jax_platforms

    reapply_jax_platforms()

    import numpy as np

    from fedamw_tpu.serving import ServingEngine
    from fedamw_tpu.serving.artifacts import (ArtifactIncompatible,
                                              export_ladder)
    from fedamw_tpu.utils.checkpoint import (CheckpointError,
                                             load_checkpoint)

    kw = {}
    if args.buckets:
        kw["buckets"] = tuple(
            int(b) for b in args.buckets.split(","))
    try:
        # one disk read serves both the engine build and the round
        # marker (state= hands the loaded dict through)
        state = load_checkpoint(args.checkpoint)
        engine = ServingEngine.load(
            args.checkpoint, model=args.model, input_dim=args.input_dim,
            feature_dtype=args.feature_dtype, state=state, **kw)
    except CheckpointError as e:
        print(f"# export_artifacts: cannot load checkpoint: {e}",
              file=sys.stderr)
        return 1
    round_idx = args.round_idx
    if round_idx is None:
        round_idx = state.get("round")

    t0 = time.perf_counter()
    manifest = export_ladder(engine, args.out_dir,
                             model_version=args.version,
                             round_idx=round_idx)
    export_s = time.perf_counter() - t0
    summary = {
        "artifact": os.path.abspath(args.out_dir),
        "schema": manifest.schema,
        "buckets": manifest.buckets,
        "rungs": len(manifest.rungs),
        "bytes": sum(r["bytes"] for r in manifest.rungs.values()),
        "export_s": round(export_s, 3),
        "host": manifest.host,
        "round_idx": manifest.round_idx,
        "model_version": manifest.model_version,
    }

    if args.check:
        try:
            t0 = time.perf_counter()
            loaded = ServingEngine.from_artifact(
                args.out_dir, checkpoint=args.checkpoint,
                model=args.model)
            load_s = time.perf_counter() - t0
        except ArtifactIncompatible as e:
            print(f"# export_artifacts: check FAILED: {e}",
                  file=sys.stderr)
            return 1
        rng = np.random.RandomState(0)
        for b in loaded.buckets:
            X = rng.randn(b, loaded.input_dim).astype(np.float32)
            want = engine.predict(X)
            got = loaded.predict(X)
            if not np.array_equal(want, got):
                print(f"# export_artifacts: check FAILED: rung {b} "
                      "logits differ from the compiled engine",
                      file=sys.stderr)
                return 1
        if loaded.compile_count != 0:
            print("# export_artifacts: check FAILED: artifact load "
                  f"path compiled {loaded.compile_count} program(s); "
                  "the cold-start contract is zero", file=sys.stderr)
            return 1
        summary["check"] = {"load_s": round(load_s, 4),
                            "compile_count": loaded.compile_count,
                            "parity": "bitwise"}

    print(json.dumps(summary))
    print(f"# exported {summary['rungs']} rungs "
          f"({summary['bytes']} bytes) in {export_s:.2f}s -> "
          f"{summary['artifact']}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
