"""graftlint: repo-native static analysis for the jax_graft invariants.

Every load-bearing guarantee in this codebase is enforced at runtime by
pins that execute ONE path: ``compile_count`` stays flat across fault
plans and weight swaps (``tests/test_serve_contract.py``,
``tests/test_faults.py``), swaps are atomic under concurrent submit
(``tests/test_rollout.py``), every accepted request lands exactly one
span (``tests/test_trace.py``). A new ``if`` on a traced value, a
``.item()`` inside a jit scope, or a lock held across an engine dispatch
ships silently until a bench regresses. graftlint proves the same
invariants at the AST, over every file, on every PR — the static twin
of the runtime pins.

Rules (stable IDs; each names the runtime pin it twins):

=======  ==============================================================
GL001    trace hazards: Python ``if``/``while``/``bool``/``int``/
         ``float``/``.item()``/``np.asarray`` on values flowing from
         jit/scan/vmap-scoped arguments. Twin of the ConcretizationError
         the fused round scan would raise — but only on the path a test
         happens to trace.
GL002    recompile hazards: fresh ``jax.jit`` construction, or array
         ``.shape``/``.dtype`` interpolated into cache keys, inside
         serving hot paths. Twin of the ``compile_count`` pins in
         tests/test_serve_contract.py and tests/test_faults.py.
GL003    host sync in serving hot paths: ``block_until_ready`` or
         implicit device->numpy conversion inside engine dispatch /
         ``_serve_batch`` / replica routing. Twin of the serve bench's
         stage-split latency accounting.
GL004    lock discipline: a ``threading.Lock`` held across a blocking
         call (engine dispatch, ``queue.get``, file I/O, ``sleep``) or
         re-acquired non-reentrantly. Twin of the swap-atomicity and
         exactly-once-span pins.
GL005    unseeded randomness / wall-clock reads inside traced code:
         ``np.random``/``random``/``time.time`` under jit bake one
         trace-time constant into every execution. Twin of the
         seeded-determinism pins in tests/test_faults.py.
GL006    exception hygiene in serving worker threads: a bare/overbroad
         ``except`` that neither counts into ``ServeMetrics``-style
         telemetry, re-raises, nor propagates the caught exception.
         Twin of the zero-lost-requests chaos pin.
=======  ==============================================================

Findings are suppressible ONLY inline::

    risky_line()  # graftlint: disable=GL003 <reason, mandatory>

(a reasonless disable does not suppress), plus a committed baseline
(``tools/graftlint/baseline.json`` — kept EMPTY: every pre-existing true
finding in the package is fixed or inline-suppressed with a reason, and
the tier-1 gate ``tests/test_graftlint.py`` holds it at zero).

Run: ``python -m tools.graftlint [--format json]`` — JSON output carries
the versioned ``GRAFTLINT.v1`` schema, gated by
``tools/check_bench_schema.py`` like every other machine-read artifact.
"""

from __future__ import annotations

import dataclasses
import hashlib

#: JSON output schema tag. Bump on any field-semantics change —
#: tools/check_bench_schema.py refuses unknown majors the same way it
#: does for BENCH_SERVE.vN artifacts.
SCHEMA = "GRAFTLINT.v1"

#: Rule ID -> (title, what it catches, the runtime pin it twins).
RULES = {
    "GL001": (
        "trace hazard",
        "Python control flow or concretization (if/while/bool/int/"
        "float/.item()/np.asarray) on a value that flows from "
        "jit/scan/vmap-scoped arguments",
        "zero-recompile scan sweep (tests/test_faults.py); "
        "ConcretizationError at trace time"),
    "GL002": (
        "recompile hazard",
        "fresh jax.jit construction, or array .shape/.dtype used as a "
        "cache/dispatch key, inside a serving hot path",
        "compile_count pins (tests/test_serve_contract.py, "
        "tests/test_faults.py)"),
    "GL003": (
        "host sync in hot path",
        "block_until_ready or device->numpy conversion inside engine "
        "dispatch / _serve_batch / replica routing",
        "serve bench stage split + latency percentiles "
        "(tests/test_serve_contract.py)"),
    "GL004": (
        "lock discipline",
        "threading lock held across a blocking call (engine dispatch, "
        "queue.get, file I/O, sleep, join) or re-acquired "
        "non-reentrantly",
        "swap-atomicity / exactly-once-span pins "
        "(tests/test_rollout.py, tests/test_replica.py)"),
    "GL005": (
        "impure traced code",
        "unseeded randomness (np.random/random) or wall-clock reads "
        "(time.time/perf_counter/datetime.now) inside traced code — "
        "baked to a trace-time constant",
        "seeded fault-plan determinism (tests/test_faults.py, "
        "tests/test_replica.py)"),
    "GL006": (
        "exception hygiene",
        "bare/overbroad except in serving-thread code that neither "
        "counts into metrics, re-raises, nor propagates the caught "
        "exception",
        "zero lost requests under chaos (tests/test_replica.py); "
        "every future resolves (tests/test_serving.py)"),
}

ALL_RULES = tuple(sorted(RULES))


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation, anchored to a source line."""

    rule: str
    path: str       # package-relative posix path
    line: int       # 1-indexed
    message: str
    context: str = ""   # stripped source line (operator orientation)
    suppressed: bool = False
    reason: str = ""    # suppression reason when suppressed
    occurrence: int = 0  # index among same-file findings with
    # identical context (two `self._rotate_locked()` sites must not
    # share a baseline fingerprint — one entry would silence both)

    @property
    def fingerprint(self) -> str:
        """Stable identity for baseline matching: rule + file +
        normalized source text + occurrence index (NOT the line
        number, so findings survive unrelated edits above them — but
        textually identical violations in one file stay distinct)."""
        blob = (f"{self.rule}|{self.path}|{self.context.strip()}"
                f"|{self.occurrence}")
        return hashlib.sha1(blob.encode()).hexdigest()[:16]

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "context": self.context,
            "fingerprint": self.fingerprint,
            **({"reason": self.reason} if self.suppressed else {}),
        }


def default_package_root() -> str:
    """The shipped package directory this repo lints tier-1 — the
    checkout path when run from the repo, else the INSTALLED
    ``fedamw_tpu`` package (the `graftlint` console script outside a
    checkout). A miss on both falls through to the CLI's loud
    missing-root error, never a silent clean."""
    import os

    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    path = os.path.join(
        repo,
        "non-iid-distributed-learning-with-optimal-mixture-weights_tpu")
    if os.path.isdir(path):
        return path
    try:
        import fedamw_tpu

        return os.path.dirname(os.path.abspath(fedamw_tpu.__file__))
    except ImportError:
        return path


def run_lint(root: str | None = None, rules=None):
    """Lint one package tree; returns ``(findings, suppressed)`` —
    the programmatic surface the tier-1 gate and the CLI share."""
    from .rules import lint_package

    return lint_package(root or default_package_root(), rules=rules)
