"""AST walker + scope inference for graftlint.

Three inference layers, all deliberately conservative (a finding must be
worth a human's attention — when resolution fails, graftlint stays
silent rather than guessing):

**Traced scopes** (GL001/GL005): a function is traced when it is
jit-decorated (``@jax.jit``, ``@partial(jax.jit, static_argnames=...)``),
passed to ``jax.jit``/``jax.vmap``/``jax.lax.scan|cond|while_loop|
fori_loop|switch|map``/``jax.checkpoint`` as a function argument, or
called FROM a traced scope with at least one traced argument — the
"functions they call within the package" closure, resolved through
same-module defs, nested defs, and package-relative imports. Within a
traced function, tracedness flows forward through assignments: a name is
traced when it derives from a traced parameter (jit ``static_argnums``/
``static_argnames`` excluded — those are Python values by contract).
Static extractors (``.shape``/``.ndim``/``.dtype``/``.size``, ``len``)
yield Python values under trace and break the flow; ``is``/``is not``
comparisons are structural (trace-time static) and never hazards. Host
escapes (``jax.debug.callback``/``jax.pure_callback``/``io_callback``/
``jax.debug.print``) do NOT propagate trace scope — their targets run on
the host by construction.

**Hot paths** (GL002/GL003): the serving dispatch surface, named
explicitly in :data:`HOT_PATHS` — the functions whose latency IS the
serve bench's p50/p95/p99. Device-flow inside them: a name assigned from
a ``predict``/``_predict`` call holds device buffers; converting it
(np.asarray/np.array/float/.item) blocks the worker thread.

**Thread scopes** (GL006): functions passed as ``threading.Thread(
target=...)`` or ``pool.submit(...)`` targets anywhere in the package,
plus every function defined in ``serving/`` (the whole module family
runs under the service's worker/watcher/hedge threads).
"""

from __future__ import annotations

import ast
import dataclasses
import os

#: Serving hot paths: module-relative posix path -> dotted qualnames.
#: The GL002/GL003 scope — extend when a new dispatch surface lands.
HOT_PATHS = {
    "serving/batcher.py": {
        # the ISSUE 13 continuous-admission loop: it runs once per
        # dispatch on the worker thread, so a host sync or a
        # shape-keyed cache here is a per-batch tax
        "admit", "drain", "rung_cut"},
    "serving/engine.py": {
        "ServingEngine._run", "ServingEngine.predict"},
    "serving/ladder.py": {
        # the learner's read path: polled against live traffic by a
        # re-bucketing controller — a shape-keyed cache here is the
        # exact recompile-hazard pattern the learned ladder exists to
        # avoid (install_rung/_warm_shape are deliberately NOT hot:
        # their compile is the budgeted, off-thread cost)
        "LadderLearner.observed_sizes", "LadderLearner.propose"},
    "serving/control.py": {
        # the ISSUE 14 control plane: admit runs on EVERY submit (the
        # cached decision read), _evaluate at the evaluation cadence
        # against live traffic, tick on the autoscaler thread — a
        # host sync or shape-keyed cache on any of them taxes the
        # admission path itself
        "AdmissionController.admit",
        "AdmissionController._evaluate", "Autoscaler.tick"},
    "serving/service.py": {
        "ServingService._worker", "ServingService._serve_batch",
        "ServingService._serve_group", "ServingService._shadow_probe",
        "ServingService._probe_worker"},
    "serving/replica.py": {
        "Replica.predict", "FailoverRouter.predict",
        "FailoverRouter._dispatch", "FailoverRouter._attempt",
        "FailoverRouter._pick"},
    "serving/transport.py": {
        # the ISSUE 15 cross-process seam: the client dispatch (runs
        # per batch on the serving worker, socket I/O under its
        # exchange lock — the GL004 surface) and the worker-side serve
        # loop (every pod request crosses it; a host sync or
        # shape-keyed cache here taxes the whole pod)
        "InProcessTransport.dispatch", "SocketTransport.dispatch",
        "PodWorker._serve_conn", "PodWorker._handle_dispatch",
        # the ISSUE 18 byzantine-hardened sync surface: announce
        # handling and the fingerprint-verified sync reply run per
        # pod frame on worker serve threads, resync blocks a
        # rejoining worker's first serve, and the client's
        # swap-announce holds the pod-wide swap lock — a host sync
        # or fresh jit on any of them stalls live dispatch
        "PodClientEngine.swap_weights", "PodWorker.resync",
        "PodWorker._handle_swap", "PodWorker._handle_sync"},
    "scenario/search.py": {
        # the ISSUE 18 hunt scheduler: the rarity pricing loop runs
        # between every scenario of a (wall-budgeted) campaign — a
        # device sync here would bill oracle wall-clock to the
        # scheduler and skew the truncation accounting
        "run_search"},
    "scenario/oracle.py": {
        # the ISSUE 16 property oracle: these run inside the scenario's
        # live serve leg (predict per pod dispatch, submit/event
        # application interleaved with the request stream) — a host
        # sync or fresh jit here would perturb the very timing and
        # recompile behavior the oracle exists to certify
        "OracleEngine.predict", "_ServeRun._submit_one",
        "_ServeRun._apply_event", "_ServeRun.drive"},
}

#: Attribute reads that yield PYTHON values on a tracer (static under
#: trace — accessing them is how shape-stable code is supposed to look).
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding",
                "weak_type", "itemsize", "nbytes"}

#: Callables that yield Python values (break traced flow). bool/int/
#: float are NOT here — calling them on a tracer is the GL001 hazard.
STATIC_CALLS = {"len", "isinstance", "type", "id", "repr", "str",
                "hasattr", "getattr"}

#: jax entry points whose function-valued arguments become traced roots
#: (positional index -> which args are functions; -1 = first arg only).
TRACE_ENTRY_SUFFIXES = (
    "jax.jit", "jax.vmap", "jax.pmap", "jax.grad", "jax.value_and_grad",
    "jax.lax.scan", "jax.lax.cond", "jax.lax.while_loop",
    "jax.lax.fori_loop", "jax.lax.switch", "jax.lax.map",
    "jax.lax.associative_scan", "jax.checkpoint", "jax.remat",
)

#: Host-escape wrappers: their callable argument runs on the HOST —
#: trace scope must not propagate through them.
HOST_ESCAPES = ("jax.debug.callback", "jax.pure_callback",
                "jax.experimental.io_callback", "jax.debug.print",
                "io_callback")


# ---------------------------------------------------------------------
# module loading / indexing
# ---------------------------------------------------------------------

@dataclasses.dataclass
class FunctionInfo:
    """One function (or method) definition in the package."""

    module: "ModuleInfo"
    qualname: str               # dotted: Class.method / outer.<locals>.inner
    node: ast.AST               # FunctionDef / AsyncFunctionDef / Lambda
    parent_class: str | None = None

    @property
    def name(self) -> str:
        return getattr(self.node, "name", "<lambda>")

    @property
    def key(self) -> tuple:
        return (self.module.rel, self.qualname)

    def params(self) -> list[str]:
        a = self.node.args
        names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
        if a.vararg:
            names.append(a.vararg.arg)
        if a.kwarg:
            names.append(a.kwarg.arg)
        return names


@dataclasses.dataclass
class ModuleInfo:
    """One parsed package module."""

    rel: str                    # posix path relative to package root
    path: str
    tree: ast.Module
    lines: list[str]
    aliases: dict               # local name -> dotted external module
    pkg_imports: dict           # local name -> (module rel, symbol)
    functions: dict = dataclasses.field(default_factory=dict)
    # qualname -> FunctionInfo (module-level + class methods + nested)

    def src(self, node: ast.AST) -> str:
        """The (first) source line of a node, stripped."""
        try:
            return self.lines[node.lineno - 1].strip()
        except (IndexError, AttributeError):
            return ""


def load_package(root: str) -> dict[str, ModuleInfo]:
    """Parse every ``.py`` under ``root`` into ModuleInfos keyed by
    package-relative posix path. Unparseable files are skipped (the
    interpreter would refuse them long before graftlint matters)."""
    modules: dict[str, ModuleInfo] = {}
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d != "__pycache__" and not d.startswith(".")]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            try:
                with open(path, encoding="utf-8") as f:
                    source = f.read()
                tree = ast.parse(source, filename=path)
            except (OSError, SyntaxError):
                continue
            mod = ModuleInfo(rel=rel, path=path, tree=tree,
                             lines=source.splitlines(),
                             aliases={}, pkg_imports={})
            _index_imports(mod)
            _index_functions(mod)
            modules[rel] = mod
    return modules


def _index_imports(mod: ModuleInfo) -> None:
    """Alias map (local name -> dotted external module) and
    package-import map (local name -> (module rel, symbol)).

    Relative imports resolve against the CONTAINING package —
    ``a/b.py`` and ``a/__init__.py`` both live in package ``a``, so
    ``from .engine import x`` inside ``serving/__init__.py`` lands on
    ``serving/engine.py`` (level N climbs N-1 packages from there)."""
    pkg = mod.rel[:-3].split("/")[:-1]

    def rel_base(level: int) -> list | None:
        climb = level - 1
        if climb > len(pkg):
            return None  # beyond the package root: unresolvable
        return pkg[:len(pkg) - climb] if climb else list(pkg)

    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                mod.aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            if node.level and node.module is not None:
                # relative: resolve against the package tree
                base = rel_base(node.level)
                if base is None:
                    continue
                target = base + node.module.split(".")
                target_rel = "/".join(target) + ".py"
                for a in node.names:
                    mod.pkg_imports[a.asname or a.name] = (
                        target_rel, a.name)
            elif node.level and node.module is None:
                base = rel_base(node.level)
                if base is None:
                    continue
                for a in node.names:
                    # from . import x -> module x.py in the package
                    target_rel = "/".join(base + [a.name]) + ".py"
                    mod.pkg_imports[a.asname or a.name] = (
                        target_rel, None)
            elif node.module is not None:
                # absolute from-import: record the dotted source so
                # `from jax import lax` classifies lax.scan correctly
                for a in node.names:
                    mod.aliases[a.asname or a.name] = (
                        f"{node.module}.{a.name}")


def _index_functions(mod: ModuleInfo) -> None:
    def visit(node, prefix, parent_class):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}"
                mod.functions[q] = FunctionInfo(
                    module=mod, qualname=q, node=child,
                    parent_class=parent_class)
                visit(child, f"{q}.<locals>.", parent_class)
            elif isinstance(child, ast.ClassDef):
                visit(child, f"{prefix}{child.name}.", child.name)
            else:
                visit(child, prefix, parent_class)

    visit(mod.tree, "", None)


# ---------------------------------------------------------------------
# name / call resolution
# ---------------------------------------------------------------------

def dotted_name(expr: ast.AST, mod: ModuleInfo) -> str | None:
    """Best-effort dotted name of a call target / attribute chain,
    resolved through the module's import aliases: ``np.asarray`` ->
    ``numpy.asarray``, ``lax.scan`` (from jax import lax) ->
    ``jax.lax.scan``. None when the base is not a plain name."""
    parts: list[str] = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if not isinstance(expr, ast.Name):
        return None
    base = mod.aliases.get(expr.id, expr.id)
    return ".".join([base] + list(reversed(parts)))


def trace_entry_kind(dotted: str | None) -> str | None:
    """'jit' / 'scan' / 'vmap' / ... when the dotted callable is a jax
    trace entry point, else None. From-imported bare names already
    arrive fully qualified (``from jax import jit`` records the alias
    ``jit -> jax.jit``, which :func:`dotted_name` applies), so a bare
    tail is NEVER accepted on its own — builtin ``map`` must not
    classify as ``jax.lax.map`` and start minting false traced roots."""
    if dotted is None:
        return None
    for full in TRACE_ENTRY_SUFFIXES:
        tail = full.split(".")[-1]
        if dotted == full:
            return tail
        if dotted.endswith("." + tail) and \
                dotted.split(".")[0] in ("jax", "lax", "jnp"):
            return tail
    return None


def is_host_escape(dotted: str | None) -> bool:
    if dotted is None:
        return False
    if dotted in HOST_ESCAPES:
        return True
    tails = {h.split(".")[-1] for h in HOST_ESCAPES}
    return (dotted.split(".")[-1] in tails
            and dotted.split(".")[0] in ("jax", "io_callback"))


def resolve_callable(expr: ast.AST, mod: ModuleInfo,
                     local_defs: dict | None = None):
    """Resolve a call target to a package FunctionInfo when possible.

    ``local_defs``: qualname-keyed nested defs visible at the call site
    (the enclosing function's locals). Returns FunctionInfo or None.
    """
    if isinstance(expr, ast.Name):
        if local_defs and expr.id in local_defs:
            return local_defs[expr.id]
        if expr.id in mod.functions:
            return mod.functions[expr.id]
        imp = mod.pkg_imports.get(expr.id)
        if imp is not None:
            target_rel, symbol = imp
            target = _lookup_module(target_rel)
            if target is not None and symbol is not None:
                return target.functions.get(symbol)
    return None


def _lookup_module(target_rel: str):
    """A package module by resolved path — direct hit first, then the
    package spelling (``serving.py`` -> ``serving/__init__.py``)."""
    mod = _PACKAGE.get(target_rel)
    if mod is None:
        mod = _PACKAGE.get(target_rel[:-3] + "/__init__.py")
    return mod


#: Set by lint_package so cross-module resolution can see every module.
_PACKAGE: dict[str, ModuleInfo] = {}


def set_package(modules: dict[str, ModuleInfo]) -> None:
    _PACKAGE.clear()
    _PACKAGE.update(modules)
    _RETURN_MEMO.clear()


# ---------------------------------------------------------------------
# traced-scope discovery
# ---------------------------------------------------------------------

def jit_static_params(call: ast.Call, fn: FunctionInfo) -> set[str]:
    """Parameter names a jit call marks static (excluded from traced)."""
    static: set[str] = set()
    names = fn.params()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(
                        n.value, str):
                    static.add(n.value)
        elif kw.arg == "static_argnums":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(
                        n.value, int) and 0 <= n.value < len(names):
                    static.add(names[n.value])
    return static


def _decorator_trace_info(fn: FunctionInfo):
    """(is_traced, static_params) from the def's decorator list."""
    for dec in fn.node.decorator_list:
        if isinstance(dec, ast.Call):
            d = dotted_name(dec.func, fn.module)
            if d is not None and d.split(".")[-1] == "partial" \
                    and dec.args:
                inner = dotted_name(dec.args[0], fn.module)
                if trace_entry_kind(inner) == "jit":
                    return True, jit_static_params(dec, fn)
            if trace_entry_kind(d) == "jit":
                return True, jit_static_params(dec, fn)
        else:
            if trace_entry_kind(dotted_name(dec, fn.module)) in (
                    "jit", "vmap", "checkpoint", "remat"):
                return True, set()
    return False, set()


def collect_trace_roots(modules: dict[str, ModuleInfo]):
    """Every (FunctionInfo, traced-param set) that enters trace scope
    directly: jit decorators, and function-valued arguments to jax
    trace entry points anywhere in the package."""
    roots: list[tuple[FunctionInfo, frozenset]] = []
    for mod in modules.values():
        for fn in list(mod.functions.values()):
            traced, static = _decorator_trace_info(fn)
            if traced:
                roots.append((fn, frozenset(
                    p for p in fn.params() if p not in static)))
        # call-site roots: jax.jit(f), lax.scan(body, ...), vmap(f)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            kind = trace_entry_kind(dotted_name(node.func, mod))
            if kind is None:
                continue
            fn_args = []
            if kind in ("cond", "switch"):
                # every function-valued argument is a traced branch
                fn_args = [a for a in node.args
                           if isinstance(a, ast.Name)]
            elif kind in ("while_loop",):
                fn_args = [a for a in node.args[:2]
                           if isinstance(a, ast.Name)]
            elif kind in ("fori_loop",):
                fn_args = [a for a in node.args[2:3]
                           if isinstance(a, ast.Name)]
            else:
                fn_args = [a for a in node.args[:1]
                           if isinstance(a, ast.Name)]
            for arg in fn_args:
                target = _resolve_name_anywhere(arg.id, mod)
                if target is None:
                    continue
                if kind == "jit":
                    static = jit_static_params(node, target)
                    traced = frozenset(p for p in target.params()
                                       if p not in static)
                else:
                    traced = frozenset(target.params())
                roots.append((target, traced))
    return roots


def _resolve_name_anywhere(name: str, mod: ModuleInfo):
    """A Name used as a function argument: module-level def, any nested
    def with that terminal name (call sites inside the enclosing
    function see it), or a package import."""
    if name in mod.functions:
        return mod.functions[name]
    for q, fi in mod.functions.items():
        if q.endswith(f".<locals>.{name}"):
            return fi
    imp = mod.pkg_imports.get(name)
    if imp is not None:
        target = _lookup_module(imp[0])
        if target is not None and imp[1] is not None:
            return target.functions.get(imp[1])
    return None


# ---------------------------------------------------------------------
# traced dataflow: GL001 / GL005 hazards inside one traced function
# ---------------------------------------------------------------------

#: numpy concretization entry points (GL001 when fed a traced value).
NUMPY_CONCRETIZERS = {"asarray", "array", "ascontiguousarray",
                      "asfortranarray", "copy"}

#: wall-clock reads (GL005 anywhere in traced code).
WALLCLOCK_CALLS = {"time.time", "time.perf_counter", "time.monotonic",
                   "time.time_ns", "time.perf_counter_ns",
                   "datetime.datetime.now", "datetime.datetime.utcnow",
                   "datetime.now", "datetime.utcnow"}


def _short(node: ast.AST, limit: int = 48) -> str:
    try:
        s = ast.unparse(node)
    except Exception:
        return "<expr>"
    return s if len(s) <= limit else s[:limit - 3] + "..."


#: Return-tracedness memo: (fn.key, frozenset(traced params)) ->
#: bool | None (None = analysis in progress; a recursive cycle reads
#: False — conservative toward fewer findings). Cleared per lint run.
_RETURN_MEMO: dict = {}


def returns_traced(fn: "FunctionInfo", traced_params) -> bool:
    """Whether ``fn``'s return value derives from its traced params —
    the interprocedural refinement that keeps trace-time-static
    helpers (kernel resolvers, structure probes returning strings /
    bools of ``len``/``isinstance``) from poisoning the caller's flow.
    """
    key = (fn.key, frozenset(traced_params))
    if key in _RETURN_MEMO:
        v = _RETURN_MEMO[key]
        return bool(v)
    _RETURN_MEMO[key] = None
    flow = TracedFlow(fn, traced_params)
    flow.run()
    _RETURN_MEMO[key] = flow.returns_traced
    return flow.returns_traced


class TracedFlow(ast.NodeVisitor):
    """Forward tracedness flow through ONE function body.

    Emits ``hazards`` — ``(rule, node, message)`` — and ``calls`` —
    ``(FunctionInfo, frozenset(traced param names))`` for package
    callees reached from this traced scope (the interprocedural edge
    the driver follows). ``returns_traced`` records whether any return
    value derives from the traced inputs (consumed by the
    return-tracedness memo above).
    """

    def __init__(self, fn: FunctionInfo, traced_params,
                 seed_traced=frozenset()):
        self.fn = fn
        self.mod = fn.module
        self.traced = set(traced_params) | set(seed_traced)
        self.hazards: list[tuple] = []
        self.calls: list[tuple] = []
        self.local_defs: dict = {}
        self.returns_traced = False

    def run(self) -> "TracedFlow":
        for stmt in self.fn.node.body:
            self.visit(stmt)
        return self

    # -- call-target resolution (shared by flow + propagation) --------
    def _call_target(self, node: ast.Call):
        """``(FunctionInfo, frozenset(traced callee params))`` for a
        package-resolvable call, else ``(None, frozenset())``."""
        target = None
        func = node.func
        if isinstance(func, ast.Name):
            target = self.local_defs.get(func.id) or \
                _resolve_name_anywhere(func.id, self.mod)
        elif isinstance(func, ast.Attribute) and \
                isinstance(func.value, ast.Name) and \
                func.value.id == "self" and self.fn.parent_class:
            target = self.mod.functions.get(
                f"{self.fn.parent_class}.{func.attr}")
        if target is None:
            return None, frozenset()
        params = target.params()
        if target.parent_class is not None and params and \
                params[0] == "self":
            params = params[1:]
        traced_params = set()
        for i, a in enumerate(node.args):
            if i < len(params) and self.is_traced(a):
                traced_params.add(params[i])
        for kw in node.keywords:
            if kw.arg is not None and kw.arg in params and \
                    self.is_traced(kw.value):
                traced_params.add(kw.arg)
        return target, frozenset(traced_params)

    # -- tracedness of an expression ----------------------------------
    def is_traced(self, node) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.traced
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                return False
            return self.is_traced(node.value)
        if isinstance(node, ast.Subscript):
            return self.is_traced(node.value)
        if isinstance(node, ast.BinOp):
            return self.is_traced(node.left) or self.is_traced(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.is_traced(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self.is_traced(v) for v in node.values)
        if isinstance(node, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot))
                   for op in node.ops):
                return False  # structural: static under trace
            return (self.is_traced(node.left)
                    or any(self.is_traced(c) for c in node.comparators))
        if isinstance(node, ast.IfExp):
            return self.is_traced(node.body) or self.is_traced(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self.is_traced(e) for e in node.elts)
        if isinstance(node, ast.Starred):
            return self.is_traced(node.value)
        if isinstance(node, ast.Call):
            dotted = dotted_name(node.func, self.mod)
            if dotted is not None and dotted.split(".")[-1] in \
                    STATIC_CALLS:
                return False
            if dotted in ("bool", "int", "float"):
                # concretized: the RESULT is a Python scalar (the call
                # itself is the GL001 hazard, reported at visit_Call)
                return False
            if isinstance(node.func, ast.Attribute) and \
                    self.is_traced(node.func.value):
                return True  # method on a traced value
            target, tp = self._call_target(node)
            if target is not None:
                # package callee: the RESULT is traced only when its
                # return value derives from the traced arguments (a
                # trace-time-static resolver returning strings/flags
                # must not poison the caller's flow)
                return returns_traced(target, tp)
            return any(self.is_traced(a) for a in node.args) or \
                any(self.is_traced(kw.value) for kw in node.keywords)
        return False

    # -- assignment flow ----------------------------------------------
    def _bind(self, target, traced: bool, value=None) -> None:
        if isinstance(target, ast.Name):
            if traced:
                self.traced.add(target.id)
            else:
                self.traced.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            if isinstance(value, (ast.Tuple, ast.List)) and \
                    len(value.elts) == len(target.elts):
                for t, v in zip(target.elts, value.elts):
                    self._bind(t, self.is_traced(v), v)
            else:
                for t in target.elts:
                    self._bind(t, traced)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, traced)
        # attribute/subscript stores: no local name to track

    def visit_Assign(self, node: ast.Assign) -> None:
        self.visit(node.value)
        traced = self.is_traced(node.value)
        for t in node.targets:
            self._bind(t, traced, node.value)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self.visit(node.value)
            self._bind(node.target, self.is_traced(node.value),
                       node.value)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self.visit(node.value)
        if isinstance(node.target, ast.Name) and \
                self.is_traced(node.value):
            self.traced.add(node.target.id)

    def visit_For(self, node: ast.For) -> None:
        self.visit(node.iter)
        self._bind(node.target, self.is_traced(node.iter))
        for stmt in node.body + node.orelse:
            self.visit(stmt)

    def visit_withitem(self, node: ast.withitem) -> None:
        self.visit(node.context_expr)
        if node.optional_vars is not None:
            self._bind(node.optional_vars,
                       self.is_traced(node.context_expr))

    # -- hazards ------------------------------------------------------
    def _hazard(self, rule: str, node: ast.AST, msg: str) -> None:
        self.hazards.append((rule, node, msg))

    def visit_If(self, node: ast.If) -> None:
        self.visit(node.test)
        if self.is_traced(node.test):
            self._hazard(
                "GL001", node,
                f"Python `if {_short(node.test)}` on a traced value — "
                "concretizes at trace time (use jnp.where / lax.cond)")
        for stmt in node.body + node.orelse:
            self.visit(stmt)

    def visit_While(self, node: ast.While) -> None:
        self.visit(node.test)
        if self.is_traced(node.test):
            self._hazard(
                "GL001", node,
                f"Python `while {_short(node.test)}` on a traced value "
                "— concretizes at trace time (use lax.while_loop)")
        for stmt in node.body + node.orelse:
            self.visit(stmt)

    def visit_IfExp(self, node: ast.IfExp) -> None:
        if self.is_traced(node.test):
            self._hazard(
                "GL001", node,
                f"conditional expression on traced `{_short(node.test)}`"
                " — concretizes at trace time (use jnp.where)")
        self.generic_visit(node)

    def visit_Assert(self, node: ast.Assert) -> None:
        if self.is_traced(node.test):
            self._hazard(
                "GL001", node,
                f"assert on traced `{_short(node.test)}` — concretizes "
                "at trace time (use checkify or a host-side check)")
        self.generic_visit(node)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # a def inside a traced scope: register for call resolution and
        # analyze with the CURRENT traced names as its closure seed
        # (scan bodies close over the enclosing jit's traced arguments)
        q = None
        for qual, fi in self.mod.functions.items():
            if fi.node is node:
                q = fi
                break
        if q is not None:
            self.local_defs[node.name] = q
            sub = TracedFlow(q, frozenset(), seed_traced=frozenset(
                self.traced))
            sub.run()
            self.hazards.extend(sub.hazards)
            self.calls.extend(sub.calls)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node: ast.Call) -> None:
        dotted = dotted_name(node.func, self.mod)
        # host escapes: the callable argument runs host-side — do not
        # walk into it or propagate trace scope through it
        if is_host_escape(dotted):
            return
        # GL001: explicit concretizers
        if dotted in ("bool", "int", "float"):
            for a in node.args:
                if self.is_traced(a):
                    self._hazard(
                        "GL001", node,
                        f"`{dotted}({_short(a)})` concretizes a traced "
                        "value at trace time")
        if dotted is not None and "." in dotted:
            base, tail = dotted.split(".", 1)
            if base == "numpy" and tail.split(".")[-1] in \
                    NUMPY_CONCRETIZERS:
                for a in node.args:
                    if self.is_traced(a):
                        self._hazard(
                            "GL001", node,
                            f"`np.{tail.split('.')[-1]}({_short(a)})` "
                            "forces a device->host transfer of a traced"
                            " value (use jnp inside traced code)")
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr == "item" and \
                self.is_traced(node.func.value):
            self._hazard(
                "GL001", node,
                f"`{_short(node.func.value)}.item()` concretizes a "
                "traced value at trace time")
        # GL005: host randomness / wall clock inside traced code
        if dotted is not None:
            if dotted.startswith("numpy.random.") or \
                    dotted == "numpy.random":
                self._hazard(
                    "GL005", node,
                    f"`{_short(node)}` — numpy randomness in traced "
                    "code runs ONCE at trace time and bakes a constant "
                    "(use jax.random with a threaded key)")
            elif dotted.split(".")[0] == "random" and \
                    self.mod.aliases.get("random", "random") == "random":
                self._hazard(
                    "GL005", node,
                    f"`{_short(node)}` — stdlib randomness in traced "
                    "code runs ONCE at trace time and bakes a constant "
                    "(use jax.random with a threaded key)")
            elif dotted in WALLCLOCK_CALLS:
                self._hazard(
                    "GL005", node,
                    f"`{_short(node)}` — wall-clock read in traced "
                    "code is baked at trace time (pass times in as "
                    "arguments)")
        # interprocedural edge: package callees reached from here
        self._propagate(node)
        self.generic_visit(node)

    def visit_Return(self, node: ast.Return) -> None:
        if node.value is not None:
            self.visit(node.value)
            if self.is_traced(node.value):
                self.returns_traced = True

    def _propagate(self, node: ast.Call) -> None:
        target, traced_params = self._call_target(node)
        if target is not None:
            self.calls.append((target, traced_params))
