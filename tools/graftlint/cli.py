"""graftlint CLI: ``python -m tools.graftlint [path] [--format json]``.

Exit status: 0 when zero unsuppressed, non-baselined findings; 1
otherwise. Text output is one finding per line (path:line: RULE
message); JSON output carries the versioned ``GRAFTLINT.v1`` schema
(gated by ``tools/check_bench_schema.py`` like the bench artifacts),
with the suppressed findings and their reasons reported alongside —
an audit trail, not a silence.
"""

from __future__ import annotations

import argparse
import json
import sys

from . import ALL_RULES, RULES, SCHEMA, default_package_root, run_lint
from .suppress import apply_baseline, load_baseline, save_baseline


def report_json(package: str, findings, suppressed, baselined,
                rules_run=None) -> dict:
    """``rules_run``: the rules this run actually executed (a
    ``--rules`` subset must not emit an artifact indistinguishable
    from a full clean run — the counts table covers exactly what
    ran, and the gate cross-checks the two)."""
    rules_run = tuple(rules_run) if rules_run else ALL_RULES
    counts = {r: 0 for r in rules_run}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    return {
        "schema": SCHEMA,
        "package": package,
        "rules": {r: {"title": t, "catches": c, "runtime_twin": twin}
                  for r, (t, c, twin) in sorted(RULES.items())},
        "rules_run": sorted(rules_run),
        "counts": counts,
        "findings": [f.to_json() for f in findings],
        "baselined": [f.to_json() for f in baselined],
        "suppressed": [f.to_json() for f in suppressed],
        "clean": not findings,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="graftlint",
        description="repo-native static analysis for the jax_graft "
                    "invariants (GL001-GL006)")
    ap.add_argument("path", nargs="?", default=None,
                    help="package root to lint (default: the shipped "
                         "package)")
    ap.add_argument("--format", choices=("text", "json"),
                    default="text")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule subset (e.g. "
                         "GL001,GL004)")
    ap.add_argument("--baseline", default=None,
                    help="baseline file (default: the committed "
                         "tools/graftlint/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline entirely")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept every current finding into the "
                         "baseline file and exit 0 (adoption aid; "
                         "this repo keeps the committed baseline "
                         "EMPTY)")
    args = ap.parse_args(argv)

    root = args.path or default_package_root()
    rules = None
    if args.rules:
        rules = tuple(r.strip() for r in args.rules.split(","))
        unknown = [r for r in rules if r not in RULES]
        if unknown:
            print(f"graftlint: unknown rule(s) {unknown}; have "
                  f"{sorted(RULES)}", file=sys.stderr)
            return 2
    try:
        findings, suppressed = run_lint(root, rules=rules)
    except FileNotFoundError as e:
        # a missing/typo'd root must never report clean (exit 2, not
        # 1: "nothing was linted" is a usage error, not a finding)
        print(str(e), file=sys.stderr)
        return 2

    if args.write_baseline:
        path = save_baseline(findings, args.baseline)
        print(f"graftlint: wrote {len(findings)} fingerprint(s) to "
              f"{path}")
        return 0

    baseline = set() if args.no_baseline else load_baseline(
        args.baseline)
    findings, baselined = apply_baseline(findings, baseline)

    if args.format == "json":
        print(json.dumps(report_json(root, findings, suppressed,
                                     baselined, rules_run=rules),
                         indent=1, sort_keys=True))
        return 1 if findings else 0

    for f in findings:
        print(f"{f.path}:{f.line}: {f.rule} {f.message}")
        if f.context:
            print(f"    {f.context}")
    for f in baselined:
        print(f"{f.path}:{f.line}: {f.rule} [baselined] {f.message}")
    if suppressed:
        print(f"-- {len(suppressed)} suppressed finding(s):")
        for f in suppressed:
            print(f"   {f.path}:{f.line}: {f.rule} ({f.reason})")
    if findings:
        print(f"graftlint: {len(findings)} finding(s) in {root}",
              file=sys.stderr)
        return 1
    print(f"graftlint: clean ({len(suppressed)} suppressed, "
          f"{len(baselined)} baselined)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
