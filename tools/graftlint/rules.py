"""Rule implementations GL001–GL006 over the scope inference.

Each rule is a pure function over the loaded package returning raw
hazards ``(rule, module, node, message)``; :func:`lint_package` runs
them, anchors findings to source lines, and applies the inline
suppressions (``suppress.py``). Scope decisions live in ``astscope.py``
— rules only pattern-match within the scopes it hands them.
"""

from __future__ import annotations

import ast
import dataclasses
from collections import deque

from . import ALL_RULES, Finding
from .astscope import (HOT_PATHS, TracedFlow, _resolve_name_anywhere,
                       _short, collect_trace_roots, dotted_name,
                       load_package, set_package, trace_entry_kind)
from .suppress import split_suppressed

#: Blocking-call classification for GL004: dotted externals.
_BLOCKING_DOTTED = {
    "time.sleep": "time.sleep",
    "open": "file I/O (open)",
    "os.listdir": "file I/O (os.listdir)",
    "os.scandir": "file I/O (os.scandir)",
    "os.remove": "file I/O (os.remove)",
    "os.makedirs": "file I/O (os.makedirs)",
    "os.rename": "file I/O (os.rename)",
    "os.replace": "file I/O (os.replace)",
    "os.stat": "file I/O (os.stat)",
    "shutil.rmtree": "file I/O (shutil.rmtree)",
    "shutil.copytree": "file I/O (shutil.copytree)",
    "subprocess.run": "subprocess.run",
    "subprocess.check_output": "subprocess",
    "wait": "concurrent.futures.wait",
    # the ISSUE 15 socket vocabulary: a lock held across any of these
    # stalls every thread contending for it by a network round-trip
    "socket.create_connection": "socket connect",
    "socket.create_server": "socket bind/listen",
}

#: Attribute-call patterns that block: attr -> (label, value-source
#: hint substrings; empty = always).
_BLOCKING_ATTRS = {
    "predict": ("engine dispatch (.predict)", ()),
    "warmup": ("ladder compile (.warmup)", ()),
    "result": ("Future.result", ()),
    "shutdown": ("executor shutdown", ()),
    "sleep": ("sleep", ()),
    "acquire": ("nested lock acquire", ()),
    "wait": ("wait", ()),
    "join": ("thread join", ("thread",)),
    "get": ("queue.get", ("_q", "queue")),
    "put": ("queue.put", ("_q", "queue")),
    "write": ("file write", ("file",)),
    "flush": ("file flush", ("file",)),
    "read": ("file read", ("file",)),
    # blocking-socket spellings (ISSUE 15): distinctive enough to
    # match unconditionally — nothing else in the package names them
    "recv": ("socket recv", ()),
    "sendall": ("socket send", ()),
    "accept": ("socket accept", ()),
    "connect": ("socket connect", ()),
}

#: GL003 device->host conversion entry points (numpy tails).
_NP_CONVERTERS = {"asarray", "array", "ascontiguousarray", "copy"}


# ---------------------------------------------------------------------
# GL001 / GL005: traced-scope hazards (interprocedural driver)
# ---------------------------------------------------------------------

def rule_traced(modules) -> list[tuple]:
    """Walk every traced root, following package-internal calls with
    traced arguments; union the traced-param sets per function so a
    callee reached from two scopes is analyzed once with both."""
    out = []
    seen: dict = {}
    queue = deque(collect_trace_roots(modules))
    guard = 0
    while queue and guard < 10_000:
        guard += 1
        fn, traced = queue.popleft()
        prev = seen.get(fn.key)
        union = (prev or frozenset()) | traced
        if prev is not None and union == prev:
            continue
        seen[fn.key] = union
        flow = TracedFlow(fn, union).run()
        for rule, node, msg in flow.hazards:
            out.append((rule, fn.module, node,
                        f"{msg} [in traced scope "
                        f"{fn.qualname}]"))
        for callee, tp in flow.calls:
            queue.append((callee, tp))
    return out


# ---------------------------------------------------------------------
# GL002 / GL003: serving hot paths
# ---------------------------------------------------------------------

def _raise_lines(fn_node) -> set[int]:
    """Line numbers inside ``raise`` statements — error paths are not
    hot, and their messages legitimately interpolate shapes."""
    lines: set[int] = set()
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Raise):
            for sub in ast.walk(node):
                if hasattr(sub, "lineno"):
                    lines.add(sub.lineno)
    return lines


def _contains_shape_attr(expr) -> ast.Attribute | None:
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Attribute) and sub.attr in ("shape",
                                                           "dtype"):
            return sub
    return None


def rule_hot_paths(modules) -> list[tuple]:
    out = []
    for rel, quals in HOT_PATHS.items():
        mod = modules.get(rel)
        if mod is None:
            continue
        for q in sorted(quals):
            fn = mod.functions.get(q)
            if fn is None:
                continue
            out.extend(_lint_hot_function(mod, fn))
    return out


def _lint_hot_function(mod, fn) -> list[tuple]:
    out = []
    raise_ln = _raise_lines(fn.node)
    device: set[str] = set()

    def mark_device(target, is_dev: bool) -> None:
        if isinstance(target, ast.Name):
            if is_dev:
                device.add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for t in target.elts:
                mark_device(t, is_dev)

    def is_dispatch(call: ast.Call) -> bool:
        f = call.func
        return isinstance(f, ast.Attribute) and f.attr in ("predict",
                                                           "_predict")

    for node in ast.walk(fn.node):
        if isinstance(node, ast.Assign) and isinstance(node.value,
                                                       ast.Call):
            if is_dispatch(node.value):
                for t in node.targets:
                    mark_device(t, True)
        if not isinstance(node, ast.Call):
            continue
        dotted = dotted_name(node.func, mod)
        # GL003: explicit device sync
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr == "block_until_ready":
            out.append((
                "GL003", mod, node,
                f"`{_short(node)}` blocks the serving thread on device "
                f"completion inside hot path {fn.qualname}"))
        # GL003: device->numpy conversion of a dispatch result
        if dotted is not None and "." in dotted:
            base, tail = dotted.rsplit(".", 1)
            if base.split(".")[0] == "numpy" and \
                    tail in _NP_CONVERTERS:
                for a in node.args:
                    if isinstance(a, ast.Name) and a.id in device:
                        out.append((
                            "GL003", mod, node,
                            f"`np.{tail}({a.id})` transfers the engine "
                            "dispatch result device->host (a blocking "
                            f"sync) inside hot path {fn.qualname}"))
        if dotted in ("float", "int"):
            for a in node.args:
                if isinstance(a, ast.Name) and a.id in device:
                    out.append((
                        "GL003", mod, node,
                        f"`{dotted}({a.id})` synchronizes on the "
                        "dispatch result inside hot path "
                        f"{fn.qualname}"))
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr == "item" and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id in device:
            out.append((
                "GL003", mod, node,
                f"`{_short(node)}` synchronizes on the dispatch result "
                f"inside hot path {fn.qualname}"))
        # GL002: fresh jit / AOT compile per dispatch
        if trace_entry_kind(dotted) == "jit":
            out.append((
                "GL002", mod, node,
                f"fresh `jax.jit` construction inside hot path "
                f"{fn.qualname} — a new jit per call compiles per "
                "call (build once at engine construction)"))
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in ("lower", "compile") and \
                node.lineno not in raise_ln:
            out.append((
                "GL002", mod, node,
                f"`.{node.func.attr}(...)` inside hot path "
                f"{fn.qualname} — explicit compilation on the "
                "dispatch path"))
        # GL002: shape/dtype interpolated into a cache/dispatch key
        if node.lineno not in raise_ln and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in ("add", "setdefault", "get"):
            for a in node.args:
                attr = _contains_shape_attr(a)
                if attr is not None:
                    out.append((
                        "GL002", mod, node,
                        f"array `.{attr.attr}` used as a cache key in "
                        f"hot path {fn.qualname} — every new shape "
                        "mints a new entry (the recompile-hazard "
                        "pattern the compile_count pins watch)"))
    # GL002: shape/dtype inside subscript keys (cache[x.shape] = ...)
    for node in ast.walk(fn.node):
        if isinstance(node, ast.Subscript) and \
                getattr(node, "lineno", 0) not in raise_ln:
            attr = _contains_shape_attr(node.slice)
            if attr is not None:
                out.append((
                    "GL002", mod, node,
                    f"array `.{attr.attr}` used as a subscript key in "
                    f"hot path {fn.qualname} — shape-keyed dispatch "
                    "mints one entry per shape"))
    return out


# ---------------------------------------------------------------------
# GL004: lock discipline
# ---------------------------------------------------------------------

def _lock_types(mod) -> dict[tuple, str]:
    """``(class or None, tail identifier) -> 'Lock'/'RLock'/...`` for
    every lock constructed in the module — keyed by the owning class so
    two classes both naming ``self._lock`` (one Lock, one RLock) do
    not shadow each other."""
    types: dict[tuple, str] = {}

    def record(node, cls):
        if not isinstance(node, ast.Assign) or \
                not isinstance(node.value, ast.Call):
            return
        d = dotted_name(node.value.func, mod)
        if d is None or not d.startswith("threading."):
            return
        kind = d.split(".")[-1]
        if kind not in ("Lock", "RLock", "Condition", "Semaphore",
                        "BoundedSemaphore"):
            return
        for t in node.targets:
            if isinstance(t, ast.Name):
                types[(None, t.id)] = kind
            elif isinstance(t, ast.Attribute):
                types[(cls, t.attr)] = kind

    for top in ast.walk(mod.tree):
        if isinstance(top, ast.ClassDef):
            for node in ast.walk(top):
                record(node, top.name)
        else:
            record(top, None)
    return types


def _lock_kind(types: dict, fn, tail: str) -> str:
    """The lock's constructor kind as seen from ``fn`` — the owning
    class's assignment first, module-level second, Lock (the strict
    default) when never seen."""
    for key in ((fn.parent_class, tail), (None, tail)):
        if key in types:
            return types[key]
    return "Lock"


def _lock_tail(expr) -> str | None:
    """The identifier a with-item locks on, when it looks like a lock."""
    if isinstance(expr, ast.Name):
        name = expr.id
    elif isinstance(expr, ast.Attribute):
        name = expr.attr
    else:
        return None
    return name if "lock" in name.lower() else None


def _direct_blocking(call: ast.Call, mod) -> str | None:
    dotted = dotted_name(call.func, mod)
    if dotted in _BLOCKING_DOTTED:
        return _BLOCKING_DOTTED[dotted]
    if dotted is not None:
        tail = dotted.split(".")[-1]
        head = dotted.split(".")[0]
        if head in ("os", "shutil", "subprocess") and \
                f"{head}.{tail}" in _BLOCKING_DOTTED:
            return _BLOCKING_DOTTED[f"{head}.{tail}"]
    if isinstance(call.func, ast.Attribute):
        attr = call.func.attr
        rec = _BLOCKING_ATTRS.get(attr)
        if rec is not None:
            label, hints = rec
            if not hints:
                return label
            try:
                vs = ast.unparse(call.func.value).lower()
            except Exception:
                vs = ""
            if any(h in vs for h in hints):
                return label
    return None


def _function_subtrees(body) -> set[int]:
    """ids of nodes inside nested function defs (they do not execute
    under the enclosing lock — only their CALL does)."""
    inner: set[int] = set()
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                for sub in ast.walk(node):
                    if sub is not node:
                        inner.add(id(sub))
    return inner


def _blocking_functions(mod) -> dict[str, str]:
    """qualname -> blocking label, to fixpoint over same-module calls."""
    blocking: dict[str, str] = {}
    changed = True
    passes = 0
    while changed and passes < 8:
        changed = False
        passes += 1
        for q, fi in mod.functions.items():
            if q in blocking:
                continue
            label = None
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                lbl = _direct_blocking(node, mod)
                if lbl is not None:
                    label = lbl
                    break
                callee = _resolve_local_call(node, fi, mod)
                if callee is not None and callee.qualname in blocking:
                    label = (f"call to {callee.qualname} "
                             f"({blocking[callee.qualname]})")
                    break
            if label is not None:
                blocking[q] = label
                changed = True
    return blocking


def _resolve_local_call(call: ast.Call, fn, mod):
    func = call.func
    if isinstance(func, ast.Name):
        target = mod.functions.get(func.id)
        if target is None:
            target = _resolve_name_anywhere(func.id, mod)
            if target is not None and target.module is not mod:
                return None  # same-module closure only (conservative)
        return target
    if isinstance(func, ast.Attribute) and \
            isinstance(func.value, ast.Name) and \
            func.value.id == "self" and fn.parent_class:
        return mod.functions.get(f"{fn.parent_class}.{func.attr}")
    return None


def _acquires_lock(fn, lock_src: str) -> bool:
    for node in ast.walk(fn.node):
        if isinstance(node, ast.With):
            for item in node.items:
                try:
                    if ast.unparse(item.context_expr) == lock_src:
                        return True
                except Exception:
                    continue
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "acquire":
            # the .acquire() spelling re-acquires just as hard as a
            # with-block does
            try:
                if ast.unparse(node.func.value) == lock_src:
                    return True
            except Exception:
                continue
    return False


def rule_locks(modules) -> list[tuple]:
    out = []
    for mod in modules.values():
        types = _lock_types(mod)
        blocking = _blocking_functions(mod)
        for q, fi in mod.functions.items():
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.With):
                    continue
                for item in node.items:
                    tail = _lock_tail(item.context_expr)
                    if tail is None:
                        continue
                    kind = _lock_kind(types, fi, tail)
                    try:
                        lock_src = ast.unparse(item.context_expr)
                    except Exception:
                        lock_src = tail
                    out.extend(_lint_lock_body(
                        mod, fi, node, lock_src, kind, blocking))
            out.extend(_lint_acquire_regions(mod, fi, types, blocking))
    return out


def _stmt_lists(fn_node):
    """Every ordered statement list in a function (bodies, else/finally
    arms) — where an ``.acquire()``'s held region is a SUFFIX, not a
    subtree."""
    for node in ast.walk(fn_node):
        for field in ("body", "orelse", "finalbody"):
            stmts = getattr(node, field, None)
            if isinstance(stmts, list) and stmts:
                yield stmts
        if isinstance(node, ast.Try):
            for handler in node.handlers:
                if handler.body:
                    yield handler.body


def _acquire_stmt(stmt, mod):
    """``(call_node, lock_src, tail)`` when ``stmt`` is a bare
    ``X.acquire()`` statement on a lock-named target, else None."""
    if not isinstance(stmt, ast.Expr) or \
            not isinstance(stmt.value, ast.Call):
        return None
    call = stmt.value
    if not isinstance(call.func, ast.Attribute) or \
            call.func.attr != "acquire":
        return None
    tail = _lock_tail(call.func.value)
    if tail is None:
        return None
    try:
        lock_src = ast.unparse(call.func.value)
    except Exception:
        lock_src = tail
    return call, lock_src, tail


def _releases(node, lock_src: str) -> bool:
    """Whether ``node``'s subtree calls ``lock_src.release()``."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and \
                isinstance(sub.func, ast.Attribute) and \
                sub.func.attr == "release":
            try:
                if ast.unparse(sub.func.value) == lock_src:
                    return True
            except Exception:
                continue
    return False


def _is_bare_release(stmt, lock_src: str) -> bool:
    """``stmt`` IS ``lock_src.release()`` — the only spelling (besides
    a try/finally release) that ends the held region UNCONDITIONALLY
    at this point in the statement list."""
    if not isinstance(stmt, ast.Expr) or \
            not isinstance(stmt.value, ast.Call):
        return False
    call = stmt.value
    if not isinstance(call.func, ast.Attribute) or \
            call.func.attr != "release":
        return False
    try:
        return ast.unparse(call.func.value) == lock_src
    except Exception:
        return False


def _lint_acquire_regions(mod, fn, types, blocking) -> list[tuple]:
    """The ``.acquire()/.release()`` spelling of GL004 (the ISSUE 12
    satellite — until now only ``with`` blocks were analyzed, leaving
    e.g. ``serving/artifacts.py:_EXPORT_LOCK`` invisible): a bare
    ``X.acquire()`` statement opens a held region running to the
    statement that releases X — the common shape being
    ``acquire(); try: ...; finally: release()``, whose try body (and
    handlers/else) executes entirely under the lock. Every finding of
    a region is ANCHORED AT ITS ACQUIRE line (the acquire is the
    decision being argued; one inline suppression there covers the
    region, mirroring how a ``with`` line is one visible decision)."""
    out = []
    for stmts in _stmt_lists(fn.node):
        for i, stmt in enumerate(stmts):
            acq = _acquire_stmt(stmt, mod)
            if acq is None:
                continue
            call, lock_src, tail = acq
            kind = _lock_kind(types, fn, tail)
            held: list = []
            for later in stmts[i + 1:]:
                if _is_bare_release(later, lock_src):
                    # the region ends ONLY where the release executes
                    # unconditionally at this nesting level
                    break
                if isinstance(later, ast.Try) and any(
                        _releases(s, lock_src)
                        for s in later.finalbody):
                    # release lives in the finally: the try body,
                    # handlers, and else all run under the lock
                    # (finally stmts beside the release are left
                    # alone — ordering them vs the release is more
                    # precision than a linter should claim)
                    held.extend(later.body)
                    for handler in later.handlers:
                        held.extend(handler.body)
                    held.extend(later.orelse)
                    break
                if _releases(later, lock_src):
                    # a CONDITIONAL or nested-def release (early-exit
                    # branch, callback body): whether it runs here is
                    # path-dependent — skip the ambiguous statement
                    # itself but KEEP scanning, because the
                    # fall-through path still holds the lock (ending
                    # the region here was a silent false negative:
                    # `if err: release(); return` followed by a sleep)
                    continue
                held.append(later)
            if held:
                out.extend(_lint_held_stmts(
                    mod, fn, held, lock_src, kind, blocking,
                    outer_with=None, anchor=call))
    return out


def _lint_lock_body(mod, fn, with_node, lock_src, kind,
                    blocking) -> list[tuple]:
    return _lint_held_stmts(mod, fn, with_node.body, lock_src, kind,
                            blocking, outer_with=with_node, anchor=None)


def _lint_held_stmts(mod, fn, stmts, lock_src, kind, blocking,
                     outer_with, anchor) -> list[tuple]:
    """Shared lock-held-region scan: ``stmts`` execute with
    ``lock_src`` held (a with-body, or an acquire/release region).
    ``anchor`` (the acquire call) re-anchors every finding to the
    region head so one argued suppression covers the region; None
    anchors at each offending node (the with spelling, where the
    region head IS the surrounding with line)."""
    out = []
    skip = _function_subtrees(stmts)

    def flag(node, msg):
        where = anchor if anchor is not None else node
        if anchor is not None:
            msg = f"{msg} (line {node.lineno}; " \
                  "acquire()/release() region)"
        out.append(("GL004", mod, where, msg))

    for stmt in stmts:
        for node in ast.walk(stmt):
            if id(node) in skip:
                continue
            if isinstance(node, ast.With) and node is not outer_with:
                for item in node.items:
                    try:
                        inner = ast.unparse(item.context_expr)
                    except Exception:
                        continue
                    if inner == lock_src and kind != "RLock":
                        flag(node,
                             f"`{lock_src}` re-acquired inside its own "
                             f"{'with-block' if outer_with is not None else 'acquire/release region'}"
                             f" in {fn.qualname} — a threading.Lock is "
                             "not reentrant; this deadlocks")
            if not isinstance(node, ast.Call):
                continue
            label = _direct_blocking(node, mod)
            if label is not None:
                flag(node,
                     f"`{lock_src}` held across {label} in "
                     f"{fn.qualname} — blocking under a lock stalls "
                     "every thread contending for it")
                continue
            callee = _resolve_local_call(node, fn, mod)
            if callee is None:
                continue
            if callee.qualname in blocking:
                flag(node,
                     f"`{lock_src}` held across call to "
                     f"{callee.qualname} ({blocking[callee.qualname]}) "
                     f"in {fn.qualname}")
            elif kind != "RLock" and lock_src.startswith("self.") and \
                    _acquires_lock(callee, lock_src):
                flag(node,
                     f"`{lock_src}` re-acquired by callee "
                     f"{callee.qualname} while held in {fn.qualname} — "
                     "a threading.Lock is not reentrant; this "
                     "deadlocks")
    return out


# ---------------------------------------------------------------------
# GL006: exception hygiene on serving threads
# ---------------------------------------------------------------------

def _thread_targets(modules):
    """FunctionInfos passed as Thread(target=...) or pool.submit(f)."""
    roots = []
    for mod in modules.values():
        for fi in mod.functions.values():
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                d = dotted_name(node.func, mod)
                cand = None
                if d is not None and d.endswith("Thread"):
                    for kw in node.keywords:
                        if kw.arg == "target":
                            cand = kw.value
                elif isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "submit" and node.args:
                    cand = node.args[0]
                if cand is None:
                    continue
                target = None
                if isinstance(cand, ast.Name):
                    target = _resolve_name_anywhere(cand.id, mod)
                elif isinstance(cand, ast.Attribute) and \
                        isinstance(cand.value, ast.Name) and \
                        cand.value.id == "self" and fi.parent_class:
                    target = mod.functions.get(
                        f"{fi.parent_class}.{cand.attr}")
                if target is not None:
                    roots.append(target)
    return roots


def _gl006_scope(modules):
    """Serving modules wholesale + thread targets (and their same-
    module callees) elsewhere."""
    scope = {}
    for rel, mod in modules.items():
        if rel.startswith("serving/"):
            for fi in mod.functions.values():
                scope[fi.key] = fi
    queue = deque(_thread_targets(modules))
    while queue:
        fi = queue.popleft()
        if fi.key in scope:
            continue
        scope[fi.key] = fi
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Call):
                callee = _resolve_local_call(node, fi, fi.module)
                if callee is not None and callee.key not in scope:
                    queue.append(callee)
    return scope


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Name):
        names = [t.id]
    elif isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    return any(n in ("Exception", "BaseException") for n in names)


def _handler_accounts(handler: ast.ExceptHandler) -> bool:
    """Whether a broad handler does something accountable with the
    failure: re-raises, uses the caught exception (stores/forwards
    it), counts it into metrics/error telemetry, or increments a
    counter (``self.requeues += 1`` — the failover accounting shape)."""
    caught = handler.name
    for node in ast.walk(handler):
        if isinstance(node, (ast.Raise, ast.AugAssign)):
            return True
        if caught is not None and isinstance(node, ast.Name) and \
                node.id == caught and isinstance(node.ctx, ast.Load):
            return True
        if isinstance(node, ast.Attribute):
            a = node.attr.lower()
            if a.startswith("record_") or "metric" in a or \
                    "error" in a or a == "set_exception":
                return True
    return False


def rule_exceptions(modules) -> list[tuple]:
    out = []
    for fi in _gl006_scope(modules).values():
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Try):
                continue
            for handler in node.handlers:
                if not _is_broad(handler):
                    continue
                if _handler_accounts(handler):
                    continue
                what = ("bare `except:`" if handler.type is None else
                        f"`except {_short(handler.type)}`")
                out.append((
                    "GL006", fi.module, handler,
                    f"{what} in serving-thread code ({fi.qualname}) "
                    "swallows the failure — count it into metrics, "
                    "re-raise typed, or narrow the exception type"))
    return out


# ---------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------

_RULE_FNS = (rule_traced, rule_hot_paths, rule_locks, rule_exceptions)


def lint_package(root: str, rules=None):
    """Run every rule over the package at ``root``; returns
    ``(findings, suppressed)`` — both sorted, deduplicated, and with
    inline suppressions applied (reasonless disables do NOT suppress).
    """
    want = set(rules) if rules else set(ALL_RULES)
    modules = load_package(root)
    if not modules:
        # a missing/typo'd root or an empty tree must FAIL loudly: a
        # gate that linted zero files and reported clean is the exact
        # silent-green failure graftlint exists to stop
        raise FileNotFoundError(
            f"graftlint: no Python modules found under {root!r} — "
            "wrong path?")
    set_package(modules)
    raw: list[tuple] = []
    for rule_fn in _RULE_FNS:
        raw.extend(rule_fn(modules))
    findings = []
    seen = set()
    for rule, mod, node, msg in raw:
        if rule not in want:
            continue
        line = getattr(node, "lineno", 0)
        key = (rule, mod.rel, line)
        if key in seen:
            continue
        seen.add(key)
        findings.append(Finding(rule=rule, path=mod.rel, line=line,
                                message=msg, context=mod.src(node)))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    # occurrence-index identical (rule, file, source-text) findings in
    # line order so their baseline fingerprints stay distinct
    occ: dict = {}
    for i, f in enumerate(findings):
        key = (f.rule, f.path, f.context.strip())
        n = occ.get(key, 0)
        occ[key] = n + 1
        if n:
            findings[i] = dataclasses.replace(f, occurrence=n)
    return split_suppressed(findings, modules)
