"""Inline suppressions + baseline handling.

A finding is suppressible ONLY inline, on its own line or the line
directly above::

    out = np.asarray(out)[:n]  # graftlint: disable=GL003 <reason>

The reason is mandatory: a bare ``disable=GL003`` does not suppress
(an unexplained opt-out is indistinguishable from a drive-by silence,
and the whole point of a repo-native linter is that every exception is
an argued one). Multiple rules: ``disable=GL003,GL004 reason...``.

The baseline (``tools/graftlint/baseline.json``) is the escape hatch
for adopting the linter on a codebase with pre-existing findings —
entries are finding fingerprints (rule + file + normalized source
text, line-number free so they survive unrelated edits). THIS repo
commits it empty: every pre-existing true finding was fixed or
inline-suppressed with a reason in the PR that introduced graftlint,
and ``tests/test_graftlint.py`` gates it at zero tier-1.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re

#: ``# graftlint: disable=GL001[,GL002...] [reason]``
_DISABLE_RE = re.compile(
    r"#\s*graftlint:\s*disable=([A-Z]{2}\d{3}(?:\s*,\s*[A-Z]{2}\d{3})*)"
    r"\s*(.*)$")

BASELINE_NAME = "baseline.json"


def parse_disables(line: str):
    """``(rules, reason)`` of a suppression comment on ``line``, or
    None. The reason may be empty — the CALLER decides that an empty
    reason does not suppress (and reports it)."""
    m = _DISABLE_RE.search(line)
    if m is None:
        return None
    rules = tuple(r.strip() for r in m.group(1).split(","))
    return rules, m.group(2).strip()


def split_suppressed(findings, modules):
    """Partition findings into (active, suppressed) per the inline
    comments in their modules. A reasonless disable suppresses nothing
    and surfaces as its own note on the finding."""
    active, suppressed = [], []
    for f in findings:
        mod = modules.get(f.path)
        verdict = None
        if mod is not None:
            for ln in (f.line, f.line - 1):
                if 1 <= ln <= len(mod.lines):
                    verdict = parse_disables(mod.lines[ln - 1])
                    if verdict is not None:
                        break
        if verdict is not None and f.rule in verdict[0]:
            rules_, reason = verdict
            if reason:
                suppressed.append(dataclasses.replace(
                    f, suppressed=True, reason=reason))
                continue
            f = dataclasses.replace(
                f, message=f.message + " [suppression ignored: "
                "no reason given — `# graftlint: "
                "disable=GLNNN <why>`]")
        active.append(f)
    return active, suppressed


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        BASELINE_NAME)


def load_baseline(path: str | None = None) -> set[str]:
    """Fingerprints accepted as pre-existing. Missing file == empty
    baseline (the strict default); a malformed file raises — a silently
    ignored baseline would un-gate every finding it listed."""
    path = path or default_baseline_path()
    if not os.path.exists(path):
        return set()
    with open(path) as f:
        obj = json.load(f)
    if not isinstance(obj, dict) or "fingerprints" not in obj or \
            not isinstance(obj["fingerprints"], list):
        raise ValueError(
            f"malformed baseline {path!r}: expected "
            '{"fingerprints": [...]}')
    return set(str(x) for x in obj["fingerprints"])


def save_baseline(findings, path: str | None = None) -> str:
    path = path or default_baseline_path()
    with open(path, "w") as f:
        json.dump({"fingerprints": sorted(
            {fi.fingerprint for fi in findings})}, f, indent=1,
            sort_keys=True)
        f.write("\n")
    return path


def apply_baseline(findings, baseline: set[str]):
    """(new, baselined) — a finding whose fingerprint is in the
    baseline does not fail the gate, but still reports."""
    new, old = [], []
    for f in findings:
        (old if f.fingerprint in baseline else new).append(f)
    return new, old
