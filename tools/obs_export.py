#!/usr/bin/env python
"""Convert repo-native observability artifacts to standard wire shapes.

The trace plane exports TRACE.v1 JSONL (``utils/trace.py``) and the
telemetry plane exports TELEMETRY.v1 snapshots (``utils.telemetry.
Registry.dump`` — what ``exp.py --trace_dir`` and the serve bench
write). Both are repo-native: compact, exact, and readable by the
repo's own tools — but nothing else speaks them. This CLI converts
either (or both at once) to:

- **OTLP-shaped JSON** (default): one document carrying
  ``resourceSpans`` (from every trace input) and ``resourceMetrics``
  (from every telemetry input) in the OpenTelemetry protocol's JSON
  encoding — hex trace/span ids (raw ids preserved as attributes),
  unix-nano timestamps via the wall/monotonic anchor each input
  carries, typed attribute values. POST the output at any
  OTLP/HTTP-JSON collector endpoint and the repo's runs land in
  whatever backend the fleet already operates.
- **Prometheus text** (``--format prometheus``): the registry
  snapshot's exposition-format rendering (trace inputs are refused in
  this mode — spans have no exposition form).

Inputs are self-describing: a file whose first JSON document carries a
``TRACE.``-family ``schema`` header line is a trace; a ``TELEMETRY.``-
family ``schema`` is a registry snapshot. Anything else is an error —
a silently-skipped input would export a partial picture wearing a
complete one's name.

Examples::

    # a traced+telemetered training run -> one OTLP document
    python exp.py --trace_dir /tmp/tr --round 4 --n_repeats 1
    python tools/obs_export.py /tmp/tr/exp1_satimage_trace.jsonl \\
        /tmp/tr/exp1_satimage_telemetry.json -o run_otlp.json

    # the serve bench's exported trace
    SERVE_TRACE=/tmp/st python serve_bench.py
    python tools/obs_export.py /tmp/st/serve_trace.jsonl -o serve.json

    # registry snapshot -> Prometheus exposition text
    python tools/obs_export.py --format prometheus \\
        /tmp/tr/exp1_satimage_telemetry.json -o metrics.prom
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # runnable from any cwd, like graftlint
    sys.path.insert(0, _REPO)

from fedamw_tpu.utils.telemetry import (TELEMETRY_SCHEMA,  # noqa: E402
                                        registry_to_otlp,
                                        render_prometheus,
                                        spans_to_otlp)
from fedamw_tpu.utils.trace import read_jsonl  # noqa: E402

#: Output schema tag of the combined OTLP document (the envelope is
#: standard OTLP JSON; the tag names OUR bundling of spans + metrics in
#: one file).
OTLP_SCHEMA = "OBS_OTLP.v1"


def classify_input(path: str) -> str:
    """``"trace"`` or ``"telemetry"``, from the file's own schema
    marker; raises ``ValueError`` for anything else."""
    with open(path) as f:
        head = f.readline().strip()
    try:
        doc = json.loads(head) if head else {}
    except json.JSONDecodeError:
        doc = {}
    if not isinstance(doc, dict) or "schema" not in doc:
        # a pretty-printed snapshot spans lines; fall back to the
        # whole document before declaring the input unclassifiable
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            doc = {}
    if not isinstance(doc, dict):
        doc = {}
    schema = str(doc.get("schema", ""))
    if schema.startswith("TRACE."):
        return "trace"
    if schema.startswith("TELEMETRY."):
        return "telemetry"
    raise ValueError(
        f"{path}: first JSON document carries schema {schema or None!r} "
        f"— need a TRACE.-family JSONL header or a {TELEMETRY_SCHEMA} "
        "snapshot")


def load_trace(path: str) -> tuple[dict | None, list[dict]]:
    """``(anchor, spans)`` from a TRACE.v1 JSONL (collector export or
    a streaming part file). The anchor pair is header-borne
    (``anchor_unix_s``/``anchor_mono_s``); streaming parts predate it
    and yield None — the OTLP output then carries the monotonic
    timeline, labeled as such."""
    header, spans = read_jsonl(path)
    anchor = None
    if "anchor_unix_s" in header and "anchor_mono_s" in header:
        anchor = {"unix_s": header["anchor_unix_s"],
                  "mono_s": header["anchor_mono_s"]}
    return anchor, spans


def load_telemetry(path: str) -> dict:
    with open(path) as f:
        dump = json.load(f)
    if not isinstance(dump, dict) or not str(
            dump.get("schema", "")).startswith("TELEMETRY."):
        raise ValueError(f"{path}: not a {TELEMETRY_SCHEMA} snapshot")
    return dump


def convert(paths, fmt: str = "otlp",
            service_name: str = "fedamw_tpu") -> str:
    """The CLI's core, importable for tests: classify every input,
    convert, return the output document as a string."""
    traces, dumps = [], []
    for path in paths:
        kind = classify_input(path)
        if kind == "trace":
            traces.append((path, *load_trace(path)))
        else:
            dumps.append((path, load_telemetry(path)))
    if fmt == "prometheus":
        if traces:
            raise ValueError(
                "prometheus format renders metric registries only; "
                f"got trace input {traces[0][0]!r} (use the default "
                "otlp format for spans)")
        if not dumps:
            raise ValueError("no telemetry snapshot inputs")
        return "\n".join(render_prometheus(d) for _, d in dumps)
    doc: dict = {"schema": OTLP_SCHEMA}
    span_bundles = []
    for path, anchor, spans in traces:
        bundle = spans_to_otlp(spans, anchor=anchor,
                               service_name=service_name)
        span_bundles.extend(bundle["resourceSpans"])
    metric_bundles = []
    for path, dump in dumps:
        bundle = registry_to_otlp(dump, service_name=service_name)
        metric_bundles.extend(bundle["resourceMetrics"])
    if span_bundles:
        doc["resourceSpans"] = span_bundles
    if metric_bundles:
        doc["resourceMetrics"] = metric_bundles
    return json.dumps(doc, indent=1)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="convert TRACE.v1 JSONL / TELEMETRY.v1 snapshots "
                    "to OTLP JSON or Prometheus text")
    ap.add_argument("inputs", nargs="+",
                    help="trace JSONL and/or telemetry snapshot files "
                         "(self-describing by schema header)")
    ap.add_argument("-o", "--out", default=None,
                    help="output path (default: stdout)")
    ap.add_argument("--format", choices=("otlp", "prometheus"),
                    default="otlp")
    ap.add_argument("--service-name", default="fedamw_tpu",
                    help="OTLP resource service.name attribute")
    args = ap.parse_args(argv)
    try:
        out = convert(args.inputs, fmt=args.format,
                      service_name=args.service_name)
    except (OSError, ValueError) as e:
        print(f"obs_export: {e}", file=sys.stderr)
        return 1
    if args.out:
        with open(args.out, "w") as f:
            f.write(out if out.endswith("\n") else out + "\n")
        n = len(args.inputs)
        print(f"obs_export: {n} input(s) -> {args.out} "
              f"({args.format})", file=sys.stderr)
    else:
        print(out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
