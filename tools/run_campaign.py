#!/usr/bin/env python
"""Run a scenario-fuzzing campaign and write the CAMPAIGN artifact.

Usage::

    python tools/run_campaign.py --seed 7 --budget 200
    python tools/run_campaign.py --seed 7 --budget 200 \
        --out CAMPAIGN_fuzz.json --regressions campaigns/regressions
    python tools/run_campaign.py --seed 7 --budget 500 --search \
        --wall-budget-s 900

Sweeps ``--budget`` composed scenarios (all derived from ``--seed``;
see ``fedamw_tpu.scenario``) through the property oracle on CPU,
writes the campaign artifact (validated by
``tools/check_bench_schema.py``), and — when a scenario violates an
invariant — shrinks it and drops the minimal repro into
``--regressions``, where the pytest collector
(``tests/test_campaign_regressions.py``) will replay it forever.

``--search`` swaps the blind grid sweep for the ISSUE 18 coverage
-guided hunter (``run_search``): rarity-scheduled candidates,
near-miss mutation, a ``CAMPAIGN.v2`` artifact with coverage
accounting and mutation lineage. ``--wall-budget-s`` (or the
``CAMPAIGN_WALL_S`` environment knob the nightly sets) bounds the
hunt by wall-clock; the artifact is marked ``truncated`` when it
fires.

Exit status: 0 when every scenario ran clean, 1 when any violated an
invariant (the artifact and repro files are written either way).

The artifact is deterministic per seed modulo ``wall_s`` and
``truncated``: the time budgets exist for CI hygiene, but a truncated
campaign's digest covers only the scenarios that ran — compare
digests between runs only at equal scenario counts.
"""

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="seeded scenario-fuzzing campaign")
    ap.add_argument("--seed", type=int, default=0,
                    help="campaign master seed (default 0)")
    ap.add_argument("--budget", type=int, default=200,
                    help="scenarios to run (default 200)")
    ap.add_argument("--out", default=None,
                    help="artifact path (default CAMPAIGN_fuzz.json "
                         "at the repo root)")
    ap.add_argument("--regressions", default=None,
                    help="directory for shrunk minimal repros "
                         "(default campaigns/regressions)")
    ap.add_argument("--no-shrink", action="store_true",
                    help="record violations without shrinking "
                         "(faster triage sweeps)")
    ap.add_argument("--time-budget-s", type=float, default=None,
                    help="stop starting new scenarios after this many "
                         "seconds (artifact is marked truncated)")
    ap.add_argument("--search", action="store_true",
                    help="coverage-guided hunt (run_search, "
                         "CAMPAIGN.v2) instead of the grid sweep")
    ap.add_argument("--wall-budget-s", type=float, default=None,
                    help="with --search: wall-clock hunt budget "
                         "(defaults to the CAMPAIGN_WALL_S env var "
                         "when set)")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress the per-scenario progress lines")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from fedamw_tpu.scenario import (PropertyOracle, ScenarioSpec,
                                     run_campaign, run_search,
                                     write_regression)

    out = args.out or os.path.join(_REPO, "CAMPAIGN_fuzz.json")
    reg_dir = args.regressions or os.path.join(_REPO, "campaigns",
                                               "regressions")
    progress = None if args.quiet else (
        lambda line: print(line, file=sys.stderr, flush=True))
    if args.search:
        wall = args.wall_budget_s
        if wall is None and os.environ.get("CAMPAIGN_WALL_S"):
            wall = float(os.environ["CAMPAIGN_WALL_S"])
        artifact = run_search(
            args.seed, args.budget, oracle=PropertyOracle(),
            shrink_failures=not args.no_shrink,
            wall_budget_s=wall, progress=progress)
    else:
        artifact = run_campaign(
            args.seed, args.budget, oracle=PropertyOracle(),
            shrink_failures=not args.no_shrink,
            time_budget_s=args.time_budget_s, progress=progress)

    written = []
    for failure in artifact["violations"]:
        shrunk = failure.get("shrunk")
        if shrunk is None:
            continue
        written.append(write_regression(
            reg_dir, ScenarioSpec.parse(shrunk["spec"]),
            shrunk["codes"], shrunk["trace"], campaign_seed=args.seed,
            note=f"campaign seed {args.seed}, scenario index "
                 f"{failure['index']}"))
    tmp = f"{out}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(artifact, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, out)

    n, bad = artifact["scenarios"], artifact["failures"]
    print(f"{n} scenario(s), {bad} with violations "
          f"({artifact['wall_s']}s) -> {out}")
    for path in written:
        print(f"  minimal repro: {path}")
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
