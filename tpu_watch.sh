#!/bin/bash
# Tunnel watcher: poll the axon TPU probe until it revives, then run the
# one-shot window harvest (tpu_window.sh) and commit its artifacts —
# so a short, unattended tunnel window is never wasted (the round-3
# review: "tpu_window.sh only runs if a human happens to be watching").
# Usage: bash tpu_watch.sh [outdir]   (env: TPU_WATCH_INTERVAL seconds,
# default 600; TPU_WATCH_MAX_POLLS caps the loop, default unbounded)
set -u
OUT=${1:-tpu_artifacts}
INTERVAL=${TPU_WATCH_INTERVAL:-600}
MAX=${TPU_WATCH_MAX_POLLS:-0}
n=0
while :; do
  if timeout 120 python -c \
      "import numpy, jax.numpy as jnp; numpy.asarray(jnp.ones(2)+1); print('TUNNEL_UP')"; then
    echo "[$(date -u +%H:%M:%S)] tunnel up — harvesting into $OUT/"
    # resume mode: skip steps a previous window already completed
    # (each drops a <step>.ok marker), so a revival spends its time
    # on what is still missing
    TPU_RESUME=${TPU_RESUME:-1} bash tpu_window.sh "$OUT"
    rc=$?
    # commit whatever landed even on partial harvest (a mid-window
    # wedge still leaves the earlier steps' artifacts)
    git add -A "$OUT" 2>/dev/null
    git commit -m "TPU window harvest: bench/pallas/scale/sweep/exp artifacts (rc=$rc)" \
      -- "$OUT" 2>/dev/null || echo "nothing new to commit"
    exit $rc
  fi
  n=$((n + 1))
  if [ "$MAX" -gt 0 ] && [ "$n" -ge "$MAX" ]; then
    echo "[$(date -u +%H:%M:%S)] giving up after $n polls"
    exit 1
  fi
  echo "[$(date -u +%H:%M:%S)] tunnel down (poll $n); retry in ${INTERVAL}s"
  sleep "$INTERVAL"
done
