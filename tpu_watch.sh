#!/bin/bash
# Tunnel watcher: poll the axon TPU probe until it revives, then run the
# one-shot window harvest (tpu_window.sh) and commit its artifacts —
# so a short, unattended tunnel window is never wasted (the round-3
# review: "tpu_window.sh only runs if a human happens to be watching").
# Usage: bash tpu_watch.sh [outdir]   (env: TPU_WATCH_INTERVAL seconds,
# default 600; TPU_WATCH_MAX_POLLS caps the loop — down-tunnel polls
# and up-tunnel partial-harvest retries both count — default unbounded)
set -u
OUT=${1:-tpu_artifacts}
INTERVAL=${TPU_WATCH_INTERVAL:-600}
MAX=${TPU_WATCH_MAX_POLLS:-0}
n=0
while :; do
  if timeout 120 python -c \
      "import numpy, jax.numpy as jnp; numpy.asarray(jnp.ones(2)+1); print('TUNNEL_UP')"; then
    echo "[$(date -u +%H:%M:%S)] tunnel up — harvesting into $OUT/"
    # resume mode: skip steps a previous window already completed
    # (each drops a <step>.ok marker), so a revival spends its time
    # on what is still missing
    TPU_RESUME=${TPU_RESUME:-1} bash tpu_window.sh "$OUT"
    rc=$?
    # commit whatever landed even on partial harvest (a mid-window
    # wedge still leaves the earlier steps' artifacts)
    git add -A "$OUT" 2>/dev/null
    git commit -m "TPU window harvest: bench/pallas/scale/sweep/exp artifacts (rc=$rc)" \
      -- "$OUT" 2>/dev/null || echo "nothing new to commit"
    # done only when every step is green, INCLUDING this window's bench
    # (bench.ok is cleared and re-dropped by tpu_window.sh each window,
    # so it certifies the current window's bench, not a stale one);
    # a partial window keeps the watcher polling for the next one —
    # without consuming the down-tunnel retry budget or mislabeling
    # the state, hence the separate branch
    if [ -e "$OUT/bench.ok" ] && [ -e "$OUT/pallas.ok" ] \
        && [ -e "$OUT/scale.ok" ] && [ -e "$OUT/bucket_sweep.ok" ] \
        && [ -e "$OUT/exp_tpu.ok" ]; then
      echo "[$(date -u +%H:%M:%S)] all steps green — watcher done"
      exit 0
    fi
    n=$((n + 1))
    if [ "$MAX" -gt 0 ] && [ "$n" -ge "$MAX" ]; then
      echo "[$(date -u +%H:%M:%S)] giving up after $n polls (last window partial, rc=$rc)"
      exit 1
    fi
    echo "[$(date -u +%H:%M:%S)] partial harvest (rc=$rc); tunnel was up — retry in ${INTERVAL}s (poll $n)"
    sleep "$INTERVAL"
    continue
  fi
  n=$((n + 1))
  if [ "$MAX" -gt 0 ] && [ "$n" -ge "$MAX" ]; then
    echo "[$(date -u +%H:%M:%S)] giving up after $n polls"
    exit 1
  fi
  echo "[$(date -u +%H:%M:%S)] tunnel down (poll $n); retry in ${INTERVAL}s"
  sleep "$INTERVAL"
done
