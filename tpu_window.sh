#!/bin/bash
# One-shot TPU-window harvest: run everything that needs the real chip,
# in priority order, saving all artifacts — so a short tunnel window is
# never wasted. Usage: bash tpu_window.sh [outdir]
# Priority: bench first (the driver's headline evidence), then Pallas
# hardware validation, then the scale configs. Each step is
# independently time-capped so one wedged compile cannot eat the window.
set -u
OUT=${1:-tpu_artifacts}
mkdir -p "$OUT"
stamp() { date -u +%H:%M:%S; }

echo "[$(stamp)] probe"
timeout 120 python -c "import numpy, jax.numpy as jnp; numpy.asarray(jnp.ones(2)+1); print('TUNNEL_UP')" \
  || { echo "tunnel down; aborting"; exit 1; }

echo "[$(stamp)] 1/4 bench.py (headline)"
timeout 1200 python bench.py >"$OUT/bench.json" 2>"$OUT/bench.log"
echo "rc=$? bench"; tail -2 "$OUT/bench.json" 2>/dev/null

echo "[$(stamp)] 2/4 pallas hardware tier"
FEDAMW_TEST_PLATFORM=tpu timeout 1200 python -m pytest tests/test_pallas_tpu.py -q \
  >"$OUT/pallas.log" 2>&1
PALLAS_RC=$?
echo "rc=$PALLAS_RC pallas"; tail -3 "$OUT/pallas.log"

echo "[$(stamp)] 3/4 scale_bench.py"
timeout 1800 python scale_bench.py >"$OUT/scale.json" 2>"$OUT/scale.log"
echo "rc=$? scale"; tail -2 "$OUT/scale.json" 2>/dev/null

echo "[$(stamp)] 4/4 bench with pallas legs explicitly (if tier passed)"
if [ "$PALLAS_RC" -eq 0 ]; then
  FEDAMW_KERNEL=pallas FEDAMW_PSOLVER=pallas timeout 1200 python bench.py \
    >"$OUT/bench_pallas.json" 2>"$OUT/bench_pallas.log"
  echo "rc=$? bench_pallas"; tail -2 "$OUT/bench_pallas.json" 2>/dev/null
else
  echo "pallas tier not green; skipping forced-pallas bench"
fi
echo "[$(stamp)] done -> $OUT/"
