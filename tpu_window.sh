#!/bin/bash
# One-shot TPU-window harvest: run everything that needs the real chip,
# in priority order, saving all artifacts — so a short tunnel window is
# never wasted. Usage: bash tpu_window.sh [outdir]
# Priority: bench first (the driver's headline evidence; its
# bench_jax_best already auto-times the XLA vs Pallas legs on TPU and
# keeps the faster one with an accuracy cross-check — do NOT force
# FEDAMW_KERNEL/FEDAMW_PSOLVER here, that would make the "xla" leg run
# pallas too and blind the cross-check), then the Pallas hardware test
# tier, then the scale configs. Each step is independently time-capped,
# and the cheap probe re-runs between steps so a mid-window tunnel
# wedge (the known crashed-Mosaic-compile failure mode) aborts in 120 s
# instead of burning every remaining step's full cap.
#
# Resumable: each step drops "$OUT/<step>.ok" on success; with
# TPU_RESUME=1 already-green steps are skipped, so a second window
# after a mid-harvest wedge spends its time only on what is missing.
# EXCEPT the bench: the headline (and its per-leg impl provenance) is
# re-measured every window — the auto kernel policy is justified by
# "re-checked per artifact", so it must never be frozen by a marker.
# The .ok markers are window-local state, not evidence: gitignored.
set -u
OUT=${1:-tpu_artifacts}
RESUME=${TPU_RESUME:-0}
mkdir -p "$OUT"
stamp() { date -u +%H:%M:%S; }
probe() {
  # asserts the real TPU backend, not just a working jax: a silent
  # CPU fallback must not let a CPU run be harvested as TPU evidence
  timeout 120 python -c "import jax, numpy, jax.numpy as jnp; \
assert jax.default_backend() in ('tpu', 'axon'), jax.default_backend(); \
numpy.asarray(jnp.ones(2)+1); print('TUNNEL_UP')" \
    || { echo "[$(stamp)] probe failed (tunnel down or non-TPU backend; see assert above); stopping (artifacts so far in $OUT/)"; exit 1; }
}
# wrap a python entrypoint so it asserts the TPU backend in ITS OWN
# process — the probe cannot see a CPU fallback inside a later process,
# and a CPU run must never be harvested as TPU evidence (mirrors
# bench.py's BENCH_STRICT_TPU)
strict_py() {  # strict_py <timeout-s> <script.py> [args...]
  # (timeout lives inside: `timeout` cannot run a shell function)
  local cap=$1 script=$2; shift 2
  timeout "$cap" python -c "
import os, sys, runpy
import jax
# mirror the entrypoints' own platform handling (the axon plugin
# latches jax_platforms at interpreter start, so the env var only
# takes effect via config.update) — a leaked JAX_PLATFORMS=cpu must
# fail the assert here, not silently downgrade the script's backend
if os.environ.get('JAX_PLATFORMS'):
    jax.config.update('jax_platforms', os.environ['JAX_PLATFORMS'])
assert jax.default_backend() in ('tpu', 'axon'), jax.default_backend()
print('$script on backend:', jax.default_backend(), file=sys.stderr)
sys.argv = ['$script'] + sys.argv[1:]
runpy.run_path('$script', run_name='__main__')
" "$@"
}
skip() { [ "$RESUME" = 1 ] && [ -e "$OUT/$1.ok" ]; }

echo "[$(stamp)] probe"; probe

echo "[$(stamp)] 1/5 bench.py (headline; auto xla-vs-pallas; never skipped)"
# STRICT: this script exists to harvest REAL-chip numbers; if the
# tunnel dies mid-step, abort fast (bench.py's default CPU fallback is
# for the driver's unattended capture, not for this window)
# bench.ok is a THIS-window success indicator, not resume state:
# cleared up front so the watcher's all-green check can't be satisfied
# by a stale marker from an earlier window while this window's bench
# failed (skip() never consults it — the bench always re-runs)
rm -f "$OUT/bench.ok"
BENCH_STRICT_TPU=1 timeout 1200 python bench.py >"$OUT/bench.json" 2>"$OUT/bench.log"
rc=$?; echo "rc=$rc bench"; [ $rc -eq 0 ] && touch "$OUT/bench.ok"
tail -2 "$OUT/bench.json" 2>/dev/null

echo "[$(stamp)] probe"; probe
if skip pallas; then echo "[$(stamp)] 2/5 pallas tier: already green, skipping"; else
echo "[$(stamp)] 2/5 pallas hardware tier"
FEDAMW_TEST_PLATFORM=tpu timeout 1200 python -m pytest tests/test_pallas_tpu.py -q \
  >"$OUT/pallas.log" 2>&1
rc=$?; echo "rc=$rc pallas"; [ $rc -eq 0 ] && touch "$OUT/pallas.ok"
tail -3 "$OUT/pallas.log"
# Consolidate the round-5 flip-back evidence in one place: the psolver
# 'auto' default reverted to xla on a red log (aggregate.py:
# resolve_psolver_impl); flipping back requires BOTH a green tier at
# HEAD (rc above) AND the mixed xla+pallas FedAMW leg beating pure
# xla (leg prints from step 1's bench). This block makes the window
# log self-contained for that decision.
{
  echo "FLIPBACK-EVIDENCE pallas_tier_rc=$rc (0 = green at HEAD)"
  # '^# FedAMW ' (not just 'leg') so the accuracy-discard and
  # leg-unavailable diagnostics travel with the timing lines — a fast
  # pair whose accuracy check discarded it must not read as a win
  grep "^# FedAMW " "$OUT/bench.log" 2>/dev/null \
    || echo "  (no FedAMW leg prints in $OUT/bench.log)"
} | tee -a "$OUT/pallas.log"
fi

echo "[$(stamp)] probe"; probe
if skip scale; then echo "[$(stamp)] 3/5 scale: already green, skipping"; else
echo "[$(stamp)] 3/5 scale_bench.py"
strict_py 1800 scale_bench.py >"$OUT/scale.json" 2>"$OUT/scale.log"
rc=$?; echo "rc=$rc scale"; [ $rc -eq 0 ] && touch "$OUT/scale.ok"
tail -2 "$OUT/scale.json" 2>/dev/null
fi

echo "[$(stamp)] probe"; probe
if skip exp_tpu; then echo "[$(stamp)] 4/5 exp.py: already green, skipping"; else
echo "[$(stamp)] 4/5 exp.py full defaults on the chip (the reference's"
echo "          own experiment — J=50, alpha=0.01, D=2000, 100 rounds,"
echo "          all 6 algorithms x 5 repeats — as a timed TPU artifact;"
echo "          CPU takes ~120 s/repeat, RESULTS.md)"
{ time strict_py 1800 exp.py --dataset digits --n_repeats 5 ; } \
  >"$OUT/exp_tpu.log" 2>&1
rc=$?; echo "rc=$rc exp"
if [ $rc -eq 0 ] && [ -f results/exp1_digits.pkl ]; then
  cp results/exp1_digits.pkl "$OUT/exp1_digits_tpu.pkl"
  touch "$OUT/exp_tpu.ok"
fi
tail -4 "$OUT/exp_tpu.log"
fi

echo "[$(stamp)] probe"; probe
if skip bucket_sweep; then echo "[$(stamp)] 5/5 sweep: already green, skipping"; else
echo "[$(stamp)] 5/5 bucket sweep (op-overhead-bound workload: where is"
echo "          the padding-vs-dispatch optimum on real hardware?)"
# BENCH_SWEEP_ONLY skips the headline/torch/reference/FedAMW legs the
# earlier steps already harvested — the 2400 s cap covers the 8 sweep
# legs (4 bucket counts + 4 unroll factors, each a compile + warm run)
BENCH_STRICT_TPU=1 BENCH_SWEEP_ONLY=1 BENCH_SWEEP_BUCKETS="8,16,32,64" \
  BENCH_SWEEP_UNROLL="1,4,8,16" \
  timeout 2400 python bench.py \
  >"$OUT/bucket_sweep.json" 2>"$OUT/bucket_sweep.log"
rc=$?; echo "rc=$rc sweep"; [ $rc -eq 0 ] && touch "$OUT/bucket_sweep.ok"
grep bucket_sweep "$OUT/bucket_sweep.json" 2>/dev/null
fi

echo "[$(stamp)] done -> $OUT/"
