#!/bin/bash
# One-shot TPU-window harvest: run everything that needs the real chip,
# in priority order, saving all artifacts — so a short tunnel window is
# never wasted. Usage: bash tpu_window.sh [outdir]
# Priority: bench first (the driver's headline evidence; its
# bench_jax_best already auto-times the XLA vs Pallas legs on TPU and
# keeps the faster one with an accuracy cross-check — do NOT force
# FEDAMW_KERNEL/FEDAMW_PSOLVER here, that would make the "xla" leg run
# pallas too and blind the cross-check), then the Pallas hardware test
# tier, then the scale configs. Each step is independently time-capped,
# and the cheap probe re-runs between steps so a mid-window tunnel
# wedge (the known crashed-Mosaic-compile failure mode) aborts in 120 s
# instead of burning every remaining step's full cap.
set -u
OUT=${1:-tpu_artifacts}
mkdir -p "$OUT"
stamp() { date -u +%H:%M:%S; }
probe() {
  timeout 120 python -c "import numpy, jax.numpy as jnp; numpy.asarray(jnp.ones(2)+1); print('TUNNEL_UP')" \
    || { echo "[$(stamp)] tunnel down; stopping (artifacts so far in $OUT/)"; exit 1; }
}

echo "[$(stamp)] probe"; probe

echo "[$(stamp)] 1/3 bench.py (headline; auto xla-vs-pallas)"
# STRICT: this script exists to harvest REAL-chip numbers; if the
# tunnel dies mid-step, abort fast (bench.py's default CPU fallback is
# for the driver's unattended capture, not for this window)
BENCH_STRICT_TPU=1 timeout 1200 python bench.py >"$OUT/bench.json" 2>"$OUT/bench.log"
echo "rc=$? bench"; tail -2 "$OUT/bench.json" 2>/dev/null

echo "[$(stamp)] probe"; probe
echo "[$(stamp)] 2/3 pallas hardware tier"
FEDAMW_TEST_PLATFORM=tpu timeout 1200 python -m pytest tests/test_pallas_tpu.py -q \
  >"$OUT/pallas.log" 2>&1
echo "rc=$? pallas"; tail -3 "$OUT/pallas.log"

echo "[$(stamp)] probe"; probe
echo "[$(stamp)] 3/4 scale_bench.py"
timeout 1800 python scale_bench.py >"$OUT/scale.json" 2>"$OUT/scale.log"
echo "rc=$? scale"; tail -2 "$OUT/scale.json" 2>/dev/null

echo "[$(stamp)] probe"; probe
echo "[$(stamp)] 4/4 bucket sweep (op-overhead-bound workload: where is"
echo "          the padding-vs-dispatch optimum on real hardware?)"
# BENCH_SWEEP_ONLY skips the headline/torch/reference/FedAMW legs the
# earlier steps already harvested — the 1200 s cap covers only the 4
# sweep compiles+runs
BENCH_STRICT_TPU=1 BENCH_SWEEP_ONLY=1 BENCH_SWEEP_BUCKETS="8,16,32,64" \
  timeout 1200 python bench.py \
  >"$OUT/bucket_sweep.json" 2>"$OUT/bucket_sweep.log"
echo "rc=$? sweep"; grep bucket_sweep "$OUT/bucket_sweep.json" 2>/dev/null

echo "[$(stamp)] done -> $OUT/"
