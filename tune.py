"""Hyperparameter-tuning trial driver (NNI-compatible).

Reproduces the reference ``tune.py`` (``/root/reference/tune.py``): one
trial = merge tuner-proposed parameters over argparse defaults (same
flag surface, ``tune.py:140-165``), run FedAMW, report the final
accuracy. NNI is import-gated — without it (as on this box) the script
runs standalone with CLI flags and prints the metric, so the same file
serves both ``nnictl create --config config.yml`` and manual sweeps.
The execution backend is selected with ``--backend`` via the registry.
"""

import argparse
import logging
import os

import numpy as np

try:
    import nni
    from nni.utils import merge_parameter

    HAS_NNI = True
except ImportError:  # tuner not installed: standalone mode
    HAS_NNI = False

logger = logging.getLogger("Tune Hyperparameters")


def get_params():
    ap = argparse.ArgumentParser(description="Tuner")
    ap.add_argument("--seed", type=int, default=1, metavar="S")
    ap.add_argument("--dataset", type=str, default="usps")
    ap.add_argument("--backend", type=str, default="jax", choices=["jax", "torch"])
    ap.add_argument("--alpha", type=float, default=0.0,
                    help="data heterogeneity parameter (synthetic)")
    ap.add_argument("--beta", type=float, default=0.0,
                    help="model heterogeneity (synthetic)")
    ap.add_argument("--D", type=int, default=2000, metavar="N")
    ap.add_argument("--kernel_par", type=float, default=0.1)
    ap.add_argument("--lambda_reg_os", type=float, default=0.000001)
    ap.add_argument("--lambda_reg", type=float, default=0.000001)
    ap.add_argument("--lambda_prox", type=float, default=0.01)
    ap.add_argument("--data_dir", type=str, default="datasets")
    ap.add_argument("--lr", type=float, default=0.5, metavar="LR")
    ap.add_argument("--lr_p", type=float, default=0.1, metavar="LR_p")
    ap.add_argument("--lr_p_os", type=float, default=0.1, metavar="LR_p")
    ap.add_argument("--local_epoch", type=int, default=2)
    ap.add_argument("--round", type=int, default=100, metavar="N")
    args, _ = ap.parse_known_args()
    return args


def main(args, metrics_out=None):
    if os.environ.get("JAX_PLATFORMS"):
        # honor the env var even under this container's sitecustomize,
        # which force-registers the axon TPU plugin (the config update
        # must land before the first backend query; with a remote-TPU
        # tunnel down, env-only selection can hang in plugin init)
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    from fedamw_tpu.config import get_parameter
    from fedamw_tpu.data import load_dataset
    from fedamw_tpu.registry import get_backend

    dataset = args["dataset"]
    registry_params = get_parameter(dataset)
    num_partitions = 50
    batch_size = 32
    alpha_dirk = 0.01

    rng = np.random.RandomState(args["seed"])
    ds = load_dataset(
        dataset, num_partitions, alpha_dirk,
        data_dir=args["data_dir"], rng=rng,
    )
    backend = get_backend(args["backend"])
    setup = backend.prepare_setup(
        ds,
        D=args["D"],
        kernel_par=registry_params["kernel_par"],
        kernel_type=registry_params["kernel_type"],
        seed=args["seed"],
        rng=rng,
    )
    res = backend.ALGORITHMS["FedAMW"](
        setup,
        lr=registry_params["lr"],
        epoch=int(args["local_epoch"]),
        batch_size=batch_size,
        lambda_reg_if=True,
        lambda_reg=args["lambda_reg"],
        round=args["round"],
        lr_p=args["lr_p"],
        seed=args["seed"],
    )
    acc = float(res["test_acc"][-1])
    loss = float(res["test_loss"][-1])
    logger.info("FedAMW --- Error: %.5f Acc: %.5f", loss, acc)
    print(f"FedAMW final: loss={loss:.5f} acc={acc:.5f}")
    if metrics_out is not None:
        # for in-process callers (sweep.py): regression trials must be
        # ranked by MSE — acc is 0.0 there (fedcore/evaluate.py), and
        # the NNI-reported value below faithfully keeps the reference's
        # acc-only report (/root/reference/tune.py:135)
        metrics_out.update(acc=acc, loss=loss)
    if HAS_NNI:
        nni.report_final_result(acc)
    return acc


if __name__ == "__main__":
    try:
        if HAS_NNI:
            tuner_params = nni.get_next_parameter()
            logger.debug(tuner_params)
            params = vars(merge_parameter(get_params(), tuner_params))
        else:
            params = vars(get_params())
        print(params)
        main(params)
    except Exception as exc:
        logger.exception(exc)
        raise
